"""Deterministic in-process multi-replica cluster — the test/bench harness.

The reference validates only end-to-end on a real IB cluster (SURVEY.md §4);
this harness runs the full protocol (election, replication, commit, pruning,
reconfig, partitions) deterministically on one host: N replicas are either N
rows of a ``vmap``-simulated axis (``mode="sim"``, any single device) or one
per device of a real mesh (``mode="spmd"``, shard_map).

Partitions/crashes are expressed through per-replica ``peer_mask`` rows —
the analog of ``reconf_bench.sh`` killing processes, but reproducible.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig, REBASE_STALL_STEPS
from rdma_paxos_tpu.consensus.log import (
    EntryType, M_CONN, M_GIDX, M_LEN, M_REQID, M_TYPE, META_W)
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.consensus.step import (
    SCAN_KEYS, StepInput, fetch_window)
from rdma_paxos_tpu.parallel.mesh import (
    build_sim_burst, build_sim_scan, build_sim_step, build_spmd_burst,
    build_spmd_scan, build_spmd_step, make_replica_mesh, stack_states)
from rdma_paxos_tpu.runtime import hostpath
from rdma_paxos_tpu.runtime.hostpath import LazyReplayStream


# Compiled steps are shared across ALL cluster engines (same static
# config ⇒ same XLA program); without this every cluster re-traces the
# protocol. Module-level so the sharded multi-group engine
# (rdma_paxos_tpu.shard.cluster.ShardedCluster) and SimCluster share
# ONE cache — a G-group cluster and a single-group cluster built from
# the same LogConfig never compile the same program twice, and tests
# can assert cache-key sets across both engines.
STEP_CACHE: Dict[tuple, object] = {}


# ---------------------------------------------------------------------------
# Shared host-bookkeeping rules — ONE implementation for BOTH engines
# (SimCluster and shard.cluster.ShardedCluster). These four rules used
# to be duplicated with a group index bolted on; any drift between the
# copies silently broke the G=1 ≡ SimCluster bit-equivalence contract,
# so the rules now live here and both engines call them (the ROADMAP
# carried-over refactor unlocking the mesh/e2e/resharding work).
# ---------------------------------------------------------------------------

def redigest_fn(cfg: LogConfig, window_slots: int):
    """Fetch (or compile once into the shared cache) the jitted range
    re-digest pass (``consensus/step.py:build_redigest``). The cache
    key carries a distinct ``"redigest"`` marker, so repair-off
    clusters' key sets (and programs) are untouched — the same
    discipline as the ``audit=``/``telemetry=`` variants."""
    key = (cfg, "redigest", int(window_slots))
    fn = STEP_CACHE.get(key)
    if fn is None:
        from rdma_paxos_tpu.consensus.step import build_redigest
        fn = build_redigest(cfg, window_slots=window_slots)
        STEP_CACHE[key] = fn
    return fn


def run_redigest(cluster, buf_row, lo: int, hi: int, *, group: int,
                 rebased_total: int, replica: int) -> int:
    """Shared range re-digest rule for BOTH engines: digest the
    committed entries ``[lo, hi)`` (raw offsets) of one replica's log
    row through the jitted pass and feed them to the ledger as
    BACKFILL windows (absolute indices, ``backfill=True`` — the
    ledger's frontier self-check is not consulted for out-of-order
    history re-reports). The stamped gidx column must equal the
    expected index for every digested entry — a recycled slot means
    the range is no longer physically present and backfilling it would
    fabricate coverage. Returns the number of indices recorded.

    Caller contract: dispatches drained (``require_drained`` — the
    pass reads device log state an in-flight donated dispatch would
    race) and ``lo >= head`` of that replica."""
    require_drained(cluster._tickets, "redigest")
    if cluster.auditor is None:
        raise RuntimeError("redigest requires an audit=True cluster")
    lo, hi = int(lo), int(hi)
    if hi <= lo:
        return 0
    W = cluster._replay_W
    fn = redigest_fn(cluster.cfg, W)
    done = 0
    start = lo
    while start < hi:
        with cluster._host_lock:
            d_fut, t_fut, g_fut = fn(buf_row, jnp.int32(start))
        dig = np.asarray(d_fut)
        trm = np.asarray(t_fut)
        gix = np.asarray(g_fut)
        n = min(hi - start, W)
        expect = np.arange(start, start + n, dtype=gix.dtype)
        if not np.array_equal(gix[:n], expect):
            bad = int(np.argmax(gix[:n] != expect))
            raise RuntimeError(
                "redigest integrity: slot of index %d holds gidx %d "
                "(recycled past the range) — cannot backfill" %
                (start + bad, int(gix[bad])))
        cluster.auditor.record_window(
            replica, start + rebased_total, dig[:n], trm[:n],
            start + n + rebased_total, group=group, backfill=True,
            step=cluster.step_index)
        done += n
        start += n
    return done


def cap_tiers(k_tiers: Sequence[int],
              max_k: Optional[int]) -> Tuple[int, ...]:
    """The governed tier-cap rule, shared by BOTH engines: the fused
    tiers bounded at ``max_k`` — always a non-empty subset of the
    engine's prewarmed ladder, so a capped dispatch can never hit an
    uncompiled program. ``max_k <= 1`` is the SERIAL step, not a
    burst tier: refuse loudly rather than silently dispatching the
    smallest burst (the SLO-shed contract promises serial)."""
    if max_k is None:
        return tuple(k_tiers)
    if int(max_k) < 2:
        raise ValueError(
            "max_k <= 1 is the serial step tier — dispatch step(), "
            "not a capped burst")
    return tuple(k for k in k_tiers if k <= int(max_k)) \
        or tuple(k_tiers[:1])


def cap_scan_tiers(cluster, K: int) -> None:
    """Validate and cap an engine's fused-dispatch tier set at ``K``
    (the benches' ``--scan K`` contract, held in ONE place next to
    ``K_TIERS``): K must be >= 2 — the smallest fused tier — and the
    burst/scan sizing then picks the smallest capped tier covering
    the backlog as usual."""
    K = int(K)
    if K < 2:
        raise ValueError(
            "scan K must be >= 2 (the smallest fused tier)")
    cluster.K_TIERS = (tuple(t for t in cluster.K_TIERS if t <= K)
                       or cluster.K_TIERS[:1])


def require_drained(tickets, site: str) -> None:
    """Serial-path rule: a fused ``step()``/``step_burst()`` while
    dispatches are in flight would finish out of FIFO order AND mutate
    the pending queues before the violation surfaced — refuse up
    front, before any batch take."""
    if tickets:
        raise RuntimeError(
            "%s() with %d in-flight dispatch(es): finish the "
            "pipeline first" % (site, len(tickets)))


def requeue_shortfall(pending: List, take: List, acc: int) -> None:
    """Step/requeue rule: appends stop entirely the step the replica
    is not leader and the device capacity clamp drops suffixes only,
    so the appended set is always a PREFIX of ``take`` — requeue the
    remainder at the FRONT of ``pending``, in order (in place)."""
    if acc < len(take):
        pending[:0] = take[acc:]


def clamp_burst_take(pending_len: int, end: int, head: int,
                     n_slots: int, max_take: int,
                     reserved: int = 0) -> int:
    """Burst capacity rule: never enqueue more than the ring can take
    without drops (mid-burst drops would reorder a connection's
    fragments against later steps). ``reserved`` subtracts appends
    already dispatched but not yet reflected in ``end`` (in-flight
    pipelined tickets)."""
    avail = (n_slots - 1) - (end - head) - reserved
    return min(pending_len, max(avail, 0), max_take)


def rebase_delta_of(heads: Sequence[int], n_slots: int) -> int:
    """Rebase frontier rule: the coordinated i32-rollover delta is the
    minimum head rounded DOWN to a multiple of ``n_slots`` (the slot
    of global index g is g % n_slots and entries do not move, so the
    subtraction must preserve the mapping). <= 0 means 'cannot fire'
    (a lagging head pins the rollover — the stall-surfacing path)."""
    if not heads:
        return 0
    return min(heads) & ~(n_slots - 1)


def decode_window(wm: np.ndarray, wd: np.ndarray, n: int,
                  replayed: List, frames: Optional[List],
                  collect_frames: bool, rebase: int = 0) -> None:
    """Replay frontier rule: batched decode of ``n`` fetched entries
    (``hostpath.decode_batch`` — one compacted payload blob + cumsum
    offset table per window, zero per-entry bytes objects), appended
    as ONE columnar batch to the lazy ``replayed`` stream and, when a
    consumer opted in, as the store-ready framed blob to ``frames``.
    The single decode implementation for both engines AND both fetch
    paths (the standalone replay fetch and the scan tier's in-dispatch
    replay rows)."""
    batch = hostpath.decode_batch(wm, wd, n, rebase)
    if batch is None:
        return
    hostpath.extend_stream(replayed, batch)
    if collect_frames:
        frames.append(batch.frames())


class StepTicket:
    """One dispatched-but-not-finished protocol step/burst.

    ``begin_step``/``begin_burst`` encode + dispatch and return one of
    these immediately (the device program runs asynchronously);
    ``finish`` blocks on the outputs and runs every post-step host
    rule. Serial ``step()``/``step_burst()`` are exactly
    ``finish(begin_*())`` — the pipelined driver simply keeps more
    than one ticket in flight."""

    __slots__ = ("kind", "out", "taken", "timeouts", "K", "bufs",
                 "applied0")

    def __init__(self, kind: str, out, taken, timeouts, K: int, bufs,
                 applied0=None):
        self.kind = kind          # "step" | "burst" | "scan"
        self.out = out            # device output pytree (futures)
        self.taken = taken        # per-replica (or [g][r]) popped rows
        self.timeouts = timeouts
        self.K = K
        self.bufs = bufs          # staging buffer set (pool-owned)
        # scan tier: the host apply cursors the dispatch staged its
        # replay window at (the readback rows start here)
        self.applied0 = applied0


class StagingPool:
    """Persistent, reusable host staging buffers for window encode.

    Allocating + zeroing the [R, B, slot_words] batch arrays every
    step was a measurable share of ``host_encode``; the pool hands out
    preallocated sets and zeroes ONLY the rows the previous user
    actually wrote (recorded at release). A set stays checked out for
    the lifetime of its ticket, so a pipelined driver can never
    overwrite a buffer an in-flight dispatch is still reading —
    double-buffering falls out of the pool discipline (depth D keeps
    at most D+1 sets alive)."""

    def __init__(self):
        self._pools: Dict[tuple, List[dict]] = {}
        self._lock = threading.Lock()

    def acquire(self, key: tuple, make) -> dict:
        with self._lock:
            pool = self._pools.setdefault(key, [])
            if pool:
                return pool.pop()
        bufs = make()
        # u8 view of the payload words: zero-copy packing target (one
        # bytes->row copy per entry instead of pad+frombuffer+copy)
        bufs["data_u8"] = bufs["data"].view(np.uint8)
        bufs["key"] = key
        return bufs

    def release(self, bufs: dict, dirty_rows) -> None:
        """Return a set; ``dirty_rows`` yields (index-tuple, n) pairs —
        the rows written since acquire — which are zeroed here so the
        next acquire starts clean without a full-buffer memset."""
        data, meta = bufs["data"], bufs["meta"]
        for idx, n in dirty_rows:
            if n > 0:
                data[idx][:n] = 0
                meta[idx][:n] = 0
        with self._lock:
            self._pools[bufs["key"]].append(bufs)


def pack_rows(bufs: dict, idx: tuple, take: Sequence[Tuple],
              slot_bytes: int) -> None:
    """Zero-copy entry packing: write (etype, conn, req, payload) rows
    straight into the staging buffers at ``idx`` (e.g. ``(r,)`` or
    ``(k, g, r)``) — the single packing rule for both engines, now one
    ``hostpath.pack_window`` batch pass per window (one payload join +
    one scatter + four column writes instead of a per-entry loop)."""
    hostpath.pack_window(bufs["data_u8"][idx], bufs["meta"][idx],
                         take, slot_bytes)


def assemble_frames(types, conns, lens, raw, idxs) -> bytes:
    """Store-ready framed blob for the client entries at ``idxs`` of a
    decoded window: ``([u32 len][u8 etype][u32 conn][payload])*``,
    built by ``hostpath.frames_from_cols`` — headers and payload
    scattered over a precomputed offset table into ONE output
    allocation (byte-golden against the previous two-pass masked
    gather; pinned by tests/test_hostpath.py). ONE implementation
    shared by SimCluster and ShardedCluster so the byte format can
    never drift between the engines (the G=1 parity contract)."""
    row = raw.shape[1]
    cl = np.minimum(lens[idxs].astype(np.int64), row)
    keep = np.arange(row, dtype=np.int64) < cl[:, None]
    blob = raw[idxs][keep].tobytes()
    offs = np.zeros(idxs.size + 1, np.int64)
    np.cumsum(cl, out=offs[1:])
    return hostpath.frames_from_cols(types[idxs], conns[idxs], cl,
                                     blob, offs)


class SimCluster:
    """N-replica protocol simulation with host-side bookkeeping."""

    # legacy alias (tests and callers key off the class attribute);
    # the SAME dict object as the module-level shared cache
    _STEP_CACHE: Dict[tuple, object] = STEP_CACHE

    def __init__(self, cfg: LogConfig, n_replicas: int,
                 group_size: Optional[int] = None, *, mode: str = "sim",
                 use_pallas: Optional[bool] = None,
                 interpret: bool = False,
                 fanout: str = "gather", stable_fast_path: bool = True,
                 audit: bool = False, flight_capacity: int = 64,
                 telemetry: bool = False, scan: bool = False,
                 txn: bool = False):
        self.cfg = cfg
        # device-resident K-window scan tier (hostpath PR): with
        # scan=True, begin_burst dispatches the fused-scan program —
        # same protocol computation as the burst, but the readback is
        # ONE consolidated minimal transfer (scalar matrix + in-
        # dispatch replay rows) instead of per-field stacks plus a
        # separate fetch dispatch. Mutable at runtime (A/B benches
        # flip it); scan-off clusters never build a scan program, so
        # their STEP_CACHE keys are untouched (tests pin it).
        self.scan = bool(scan)
        self.scan_dispatches = 0
        self.R = n_replicas
        self.group_size = group_size or n_replicas
        self._mode = mode
        # correctness observability (obs/audit.py): audit=True compiles
        # the digest-chain step variants (distinct cache keys — the
        # default programs are untouched), feeds every step's digest
        # windows to a cluster AuditLedger, and records a bounded
        # flight ring of step inputs/outputs for post-mortem dumps
        self._audit = audit
        if audit:
            from rdma_paxos_tpu.obs.audit import (
                AuditLedger, FlightRecorder)
            self.auditor = AuditLedger(n_replicas)
            self.flight = FlightRecorder(flight_capacity)
        else:
            self.auditor = None
            self.flight = None
        # device telemetry (obs/device.py): telemetry=True compiles the
        # counter-vector step variants (distinct cache keys — default
        # programs untouched, exactly the audit= discipline), reduces
        # each dispatch's vectors host-side at finish() (the readback
        # thread under the pipelined driver), accumulates them into
        # ``device_counters`` [R, T_N], and exports device_* registry
        # series when an obs facade is attached
        self._telemetry = telemetry
        if telemetry:
            from rdma_paxos_tpu.obs import device as _device
            self.device_counters = _device.zeros(n_replicas)
        else:
            self.device_counters = None
        # cross-group transaction lane (txn/lane.py): txn=True compiles
        # the prepare-vote step variants (distinct cache keys — default
        # programs untouched, exactly the audit=/telemetry= discipline;
        # tests/test_txn.py pins txn=False bit-identity). The armed
        # watch is host state in the ABSOLUTE index domain; begin_step
        # converts to the log-offset domain the device compares in.
        self._txn = txn
        self._txn_watch = -1      # absolute prepare index (-1 = clear)
        self._txn_wterm = 0       # term the prepare was appended under
        # production default: the Pallas quorum kernel on TPU (same code
        # path as the benches), jnp reference scan elsewhere
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._use_pallas = use_pallas
        self._interpret = interpret
        self._fanout = fanout
        # dispatch the elections-free STABLE step on iterations where no
        # election timer fired (the latency hot path — Phase B statically
        # removed, one fewer collective); compiled lazily on first use
        self._stable_fast_path = stable_fast_path
        # the donated device-state handle: REBINDING it races the next
        # dispatch  # guarded-by: _host_lock [writes]
        self.state = stack_states(cfg, n_replicas, self.group_size)
        if mode == "spmd":
            mkey = (cfg, n_replicas, "mesh")
            if mkey not in self._STEP_CACHE:
                self._STEP_CACHE[mkey] = make_replica_mesh(n_replicas)
            self.mesh = self._STEP_CACHE[mkey]
            self._step = self._build_step(elections=True)
            self.state = jax.device_put(
                self.state,
                jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec("replica")))
        else:
            self._step = self._build_step(elections=True)
        # all replicas' windows in ONE dispatch (the per-replica loop of
        # fetch+slice dispatches dominated the host replay path). The
        # REPLAY window is wider than the protocol window: a K-step
        # burst commits up to K*batch_slots entries at once, and each
        # fetch dispatch costs host time — sweep in big gulps.
        self._replay_W = min(cfg.n_slots // 2,
                             max(4 * cfg.window_slots, 256))
        self._fetch_all = jax.jit(jax.vmap(
            lambda log, start: fetch_window(
                log, start, window_slots=self._replay_W)))
        # host bookkeeping
        # host apply cursor — single-writer: advanced in-place by the
        # finishing (readback) thread only; whole-array WRITES rebind
        # under the lock  # guarded-by: _host_lock [writes]
        self.applied = np.zeros(n_replicas, np.int64)
        self.peer_mask = np.ones((n_replicas, n_replicas), np.int32)
        # guarded-by: _host_lock
        self.pending: List[List[Tuple[int, int, int, bytes]]] = [
            [] for _ in range(n_replicas)]
        # pipelined dispatch (begin_*/finish): FIFO of in-flight
        # tickets, the staging-buffer pool, and the dispatch
        # concurrency counters (max_inflight_dispatches is the
        # acceptance witness that the pipeline really overlapped).
        # _host_lock guards the host queues (pending/applied/last)
        # against the dispatch-thread/readback-thread split — serial
        # callers pay one uncontended acquire.
        # guarded-by: _host_lock
        self._tickets: collections.deque = collections.deque()
        self._staging = StagingPool()
        self._host_lock = threading.RLock()
        self.inflight_dispatches = 0         # guarded-by: _host_lock
        self.max_inflight_dispatches = 0     # guarded-by: _host_lock
        # published by pointer swap under the lock; lock-free READS see
        # a complete (stale at worst) result dict by design
        # guarded-by: _host_lock [writes]
        self.last: Optional[Dict[str, np.ndarray]] = None
        # (type, conn_id, req_id, payload) per replica, in apply order
        # — columnar LazyReplayStream batches on the hot path, legacy
        # tuple view on demand (tests/models/recovery)
        self.replayed: List[LazyReplayStream] = [
            LazyReplayStream() for _ in range(n_replicas)]
        # store-ready framed blobs (([u32 len][etype][conn][payload])*)
        # built VECTORIZED during the window decode — the driver hands
        # them to StableStore.append_framed untouched. Only produced
        # when a consumer opts in (collect_frames), so pure-sim tests
        # don't accumulate them.
        self.collect_frames = False
        self.frames: List[List[bytes]] = [[] for _ in range(n_replicas)]
        # replicas whose log was force-pruned past their apply cursor
        # (force_log_pruning left them behind): replay stops — recycled
        # slots must never reach the app — until snapshot recovery
        self.need_recovery: set = set()
        self._wedged: set = set()     # test hook: frozen apply (wedged app)
        # coordinated i32-offset rollovers performed (see _maybe_rebase)
        self.rebases = 0
        self.rebased_total = 0
        # rebase-stall surfacing (ADVICE.md #3): a heard-but-lagging
        # row's low head pins the agreed delta at 0, so end marches
        # toward the i32 ceiling with no rollover possible. Consecutive
        # post-threshold steps with delta 0 are counted; past
        # REBASE_STALL_STEPS each further step increments
        # ``rebase_stalled`` (and the attached registry's counter), and
        # the transition emits one ``rebase_stalled`` trace event
        # (re-armed by the next successful rollover).
        self.rebase_stall_steps = 0
        self.rebase_stalled = 0
        # host-side observability facade (rdma_paxos_tpu.obs); attached
        # by ClusterDriver (or tests). NEVER read inside jitted code —
        # instrumentation must not change compiled-step cache keys.
        self.obs = None
        # optional obs.spans.StepPhaseProfiler: attributes step wall
        # time to phases (host encode / device dispatch / optional
        # fenced device sync / quorum-wait readback / apply). Host-side
        # only; with fence off it never blocks and never imports jax.
        self.profiler = None
        # pluggable per-link fault model (rdma_paxos_tpu.chaos.faults
        # .LinkModel): when attached, each step's peer_mask INPUT is
        # rewritten host-side into the effective hear-matrix
        # (asymmetric breaks, seeded drop/delay/dup, crashed
        # replicas). Purely a data rewrite — compiled-step cache keys
        # are unchanged (tests/test_chaos.py guards it). step_index is
        # the logical clock the model's per-step randomness keys on.
        self.link_model = None
        # read-path subsystem (runtime/reads.py, attached via
        # reads.attach): step-domain leader leases observed — and the
        # queued read hub drained — at the tail of every finish(),
        # which under the pipelined driver is the readback thread.
        # Pure host bookkeeping: never enters jitted code, adds no
        # STEP_CACHE keys (tests/test_reads.py pins it).
        self.leases = None
        self.reads = None
        # log-as-product streams hub (streams/__init__.py, attached
        # via streams.attach): observed at the finish() tail AFTER the
        # read drain (watch cursors follow the same committed frontier
        # reads serve from) and BEFORE the governor (a deep watch
        # backlog is demand the governor must see). Pure host-side
        # consumer: never enters jitted code, adds no STEP_CACHE keys
        # (tests/test_streams.py pins it).
        self.streams = None
        # adaptive dispatch governor (runtime/governor.py, attached
        # via governor.attach_governor): observed at the tail of every
        # finish() — the readback thread under the pipelined driver —
        # exactly like leases/reads. Pure host bookkeeping: the tier
        # it picks is always one of the prewarmed K_TIERS programs,
        # so it adds no STEP_CACHE keys (tests/test_governor.py pins
        # the ladder-only contract).
        self.governor = None
        # cross-group 2PC coordinator (txn/coordinator.py, attached via
        # txn.attach_coordinator): observed at the very tail of every
        # finish() — after the governor, so admission demand it creates
        # is next-step demand. Pure host bookkeeping; the device lane
        # it reads rides the txn= step variant's cache keys only.
        self.txn = None
        # replicas barred from SERVING reads by the repair pipeline
        # (digest quarantine AND the storm policy, whose holds leave
        # replay running and so never enter need_recovery) — consulted
        # by the KVS serving gate and the read hub; keys match
        # need_recovery's shape (r here, (g, r) on the sharded engine)
        self.read_blocked: set = set()
        self.step_index = 0
        # dispatch-side logical clock: advances at begin_* (step_index
        # advances at finish) so an in-flight pipeline never feeds the
        # link model the same per-step randomness twice; serial callers
        # see the two clocks equal at every dispatch.
        self._dispatch_clock = 0
        # runtime lock sanitizer (analysis/runtime_guard.py): under
        # RP_SANITIZE=1 the guarded-by declarations above become
        # per-access lock-ownership assertions — a latent unlocked
        # mutation fails the test at the exact access. No-op otherwise.
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_host_lock", __file__)

    # ---------------- client-side API ----------------

    def submit(self, replica: int, payload: bytes,
               etype: EntryType = EntryType.SEND, conn: int = 1,
               req_id: int = 0) -> None:
        """Queue a client entry for the next step on `replica` (it only
        enters the log if that replica is leader — proxy semantics).
        Locked: a concurrent ``begin_*`` batch take swaps the pending
        list object, and an unlocked append to the old object would be
        silently lost."""
        with self._host_lock:
            self.pending[replica].append(
                (int(etype), conn, req_id, payload))

    def submit_many(self, replica: int,
                    entries: Sequence[Tuple[int, int, int, bytes]]
                    ) -> None:
        """Queue a whole intake batch of ``(etype, conn, req_id,
        payload)`` rows in one locked extend — the drivers' batched
        intake (a per-entry ``submit`` loop was a measurable share of
        the pump under full windows)."""
        with self._host_lock:
            self.pending[replica].extend(entries)

    def set_txn_watch(self, index: int, term: int) -> None:
        """Arm the prepare watch: every subsequent serial step reports a
        per-replica vote for whether ABSOLUTE log index ``index`` is
        committed under ``term`` (txn=True clusters only). The watch is
        sticky until :meth:`clear_txn_watch` — the coordinator re-reads
        the vote matrix each step while a prepare is outstanding."""
        if not self._txn:
            raise RuntimeError("set_txn_watch requires txn=True")
        self._txn_watch = int(index)
        self._txn_wterm = int(term)

    def clear_txn_watch(self) -> None:
        self._txn_watch = -1
        self._txn_wterm = 0

    def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Split the cluster: replicas hear only same-group peers."""
        if self._fanout == "psum":
            # the O(W) psum fan-out assumes at most one self-claimed
            # leader (full connectivity); two partitioned leaders would
            # SUM their windows into followers' logs — reject loudly
            # (see replica_step's fanout docstring)
            raise ValueError(
                "partitions cannot be modeled with fanout='psum'; "
                "build the cluster with fanout='gather'")
        self.peer_mask[:] = 0
        for g in groups:
            for i in g:
                for j in g:
                    self.peer_mask[i, j] = 1
        np.fill_diagonal(self.peer_mask, 1)

    def heal(self) -> None:
        self.peer_mask[:] = 1

    def wedge_apply(self, r: int) -> None:
        """Freeze replica ``r``'s apply progress (models a wedged app:
        the host stops consuming committed entries while the replica
        keeps acking windows)."""
        self._wedged.add(r)

    def unwedge_apply(self, r: int) -> None:
        self._wedged.discard(r)

    # ---------------- stepping ----------------

    def _effective_mask(self):
        """The step's hear-matrix: the base peer_mask, refined by the
        attached link model (host-side only; psum fan-out still
        requires the EFFECTIVE mask to be full)."""
        if self.link_model is None:
            return self.peer_mask
        return self.link_model.effective_mask(self.peer_mask,
                                              self._dispatch_clock)

    # burst size tiers: the smallest tier >= the steps needed is compiled
    # (bounded recompiles) and padded with zero-count steps
    K_TIERS = (2, 4, 8, 16)

    # step() result keys pulled to host numpy each dispatch
    RES_KEYS = ("term", "role", "leader_id", "voted_term", "voted_for",
                "head", "apply", "commit", "end", "hb_seen",
                "became_leader", "acked", "accepted", "peer_acked",
                "leadership_verified", "rebase_delta")

    def _step_bufs(self) -> dict:
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        return self._staging.acquire(
            ("step", R, B), lambda: dict(
                data=np.zeros((R, B, cfg.slot_words), np.int32),
                meta=np.zeros((R, B, META_W), np.int32)))

    def _burst_bufs(self, K: int) -> dict:
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        return self._staging.acquire(
            ("burst", K, R, B), lambda: dict(
                data=np.zeros((K, R, B, cfg.slot_words), np.int32),
                meta=np.zeros((K, R, B, META_W), np.int32)))

    # holds-lock: _host_lock
    def reserved_appends(self) -> np.ndarray:
        """Per-replica appends dispatched but not yet finished — the
        pipelined capacity reservation (``end`` has not caught up).
        Callers hold ``_host_lock`` (begin_burst's capacity sizing and
        the chaos runner's drained-serial room check)."""
        out = np.zeros(self.R, np.int64)
        for t in self._tickets:
            for r in range(self.R):
                out[r] += len(t.taken[r])
        return out

    def begin_step(self, timeouts: Sequence[int] = (),
                   take_batch: bool = True) -> StepTicket:
        """Encode + DISPATCH one protocol step; returns immediately
        with the in-flight ticket (pass to :meth:`finish`, FIFO). With
        ``take_batch=False`` no client entries are packed (heartbeat /
        election dispatches of the pipelined driver, which routes all
        appends through capacity-clamped bursts so a shortfall requeue
        can never reorder against in-flight dispatches)."""
        timeouts = list(timeouts)       # may be a one-shot iterable
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        bufs = self._step_bufs()
        count = np.zeros((R,), np.int32)
        with self._host_lock:
            taken = []
            for r in range(R):
                take = self.pending[r][:B] if take_batch else []
                if take:
                    self.pending[r] = self.pending[r][B:]
                taken.append(take)
            qdepth = np.array([len(q) for q in self.pending], np.int32)
            applied = self.applied.astype(np.int32)
        for r, take in enumerate(taken):
            if take:
                pack_rows(bufs, (r,), take, cfg.slot_bytes)
                count[r] = len(take)
        tmo = np.zeros((R,), np.int32)
        for r in timeouts:
            tmo[r] = 1
        inp = StepInput(
            batch_data=jnp.asarray(bufs["data"]),
            batch_meta=jnp.asarray(bufs["meta"]),
            batch_count=jnp.asarray(count),
            timeout_fired=jnp.asarray(tmo),
            peer_mask=jnp.asarray(mask),
            apply_done=jnp.asarray(applied),
            queue_depth=jnp.asarray(qdepth),
            **(dict(
                # device watch compares log offsets: shift the armed
                # ABSOLUTE index by the i32 rollovers applied so far
                txn_watch=jnp.full(
                    (R,), (self._txn_watch - self.rebased_total
                           if self._txn_watch >= 0 else -1), jnp.int32),
                txn_term=jnp.full((R,), self._txn_wterm, jnp.int32),
            ) if self._txn else {}),
        )
        # no timer fired ⟹ Phase B is provably a no-op: dispatch the
        # stable step (bit-identical outputs, one fewer collective)
        fn = (self._build_step(elections=False)
              if self._stable_fast_path and not timeouts
              else self._step)
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        with self._host_lock:
            self.state, out = fn(self.state, inp)
            ticket = StepTicket("step", out, taken, timeouts, 1, bufs)
            self._tickets.append(ticket)
            self.inflight_dispatches += 1
            self.max_inflight_dispatches = max(
                self.max_inflight_dispatches, self.inflight_dispatches)
        if prof is not None:
            prof.stop("device_dispatch")
        self._dispatch_clock += 1
        return ticket

    def _tiers(self, max_k: Optional[int]) -> Tuple[int, ...]:
        """Fused tiers bounded at ``max_k`` (the shared ``cap_tiers``
        rule — a subset of ``K_TIERS``, never a new compile)."""
        return cap_tiers(self.K_TIERS, max_k)

    def begin_burst(self, max_k: Optional[int] = None) -> StepTicket:
        """Encode + DISPATCH up to ``max(K_TIERS)`` fused protocol
        steps; returns immediately with the in-flight ticket. Capacity
        sizing subtracts appends reserved by OTHER in-flight tickets,
        so pipelined bursts can never overrun the ring (a mid-burst
        drop would reorder a connection's fragments). ``max_k`` caps
        the tier choice (and the take) at a lower rung of the same
        ladder — the governor's dial."""
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        assert self.last is not None, "burst requires a stepped cluster"
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        tiers = self._tiers(max_k)
        with self._host_lock:
            # capacity sizing: never enqueue more than the ring can
            # take without drops, so mid-burst drops (which would
            # reorder a connection's fragments against later steps)
            # cannot occur
            reserved = self.reserved_appends()
            last = self.last
            taken: List[List[Tuple[int, int, int, bytes]]] = []
            take_n = []
            for r in range(R):
                n = clamp_burst_take(
                    len(self.pending[r]), int(last["end"][r]),
                    int(last["head"][r]), cfg.n_slots,
                    tiers[-1] * B, int(reserved[r]))
                take_n.append(n)
                taken.append(self.pending[r][:n])
                self.pending[r] = self.pending[r][n:]
            qdepth = np.array([len(q) for q in self.pending], np.int32)
            applied = self.applied.astype(np.int32)
        k_needed = max(1, max(-(-n // B) for n in take_n))
        K = next(k for k in tiers if k >= k_needed)
        bufs = self._burst_bufs(K)
        count = np.zeros((K, R), np.int32)
        for r in range(R):
            n = take_n[r]
            for k in range(-(-n // B) if n else 0):
                pack_rows(bufs, (k, r), taken[r][k * B:(k + 1) * B],
                          cfg.slot_bytes)
            for k in range(K):
                count[k, r] = max(0, min(n - k * B, B))
        scan = self.scan
        fn = self._scan_fn(K) if scan else self._burst_fn(K)
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        with self._host_lock:
            self.state, outs = fn(
                self.state, jnp.asarray(bufs["data"]),
                jnp.asarray(bufs["meta"]), jnp.asarray(count),
                jnp.asarray(mask), jnp.asarray(applied),
                jnp.asarray(qdepth))
            ticket = StepTicket("scan" if scan else "burst", outs,
                                taken, (), K, bufs,
                                applied0=applied if scan else None)
            if scan:
                self.scan_dispatches += 1
            self._tickets.append(ticket)
            self.inflight_dispatches += 1
            self.max_inflight_dispatches = max(
                self.max_inflight_dispatches, self.inflight_dispatches)
        if prof is not None:
            prof.stop("device_dispatch")
        self._dispatch_clock += K
        return ticket

    def finish(self, ticket: StepTicket) -> Dict[str, np.ndarray]:
        """Block on ``ticket``'s outputs and run every post-step host
        rule (requeue, replay, audit, flight, rebase, spans) — tickets
        MUST finish in dispatch order. ``step()``/``step_burst()`` are
        exactly ``finish(begin_*())``; the pipelined driver finishes
        from its readback thread while the next dispatch encodes."""
        assert self._tickets and self._tickets[0] is ticket, \
            "tickets must finish in dispatch (FIFO) order"
        # NOT popped here: until ``last`` below reflects this ticket's
        # appends, a concurrent ``begin_*`` must keep counting them via
        # reserved_appends() — an early pop would let its capacity
        # clamp over-admit (and a lockless pop would mutate the deque
        # under the dispatch thread's locked iteration)
        prof = self.profiler
        out = ticket.out
        burst = ticket.kind == "burst"
        scan = ticket.kind == "scan"
        if prof is not None:
            prof.sync(out)              # fenced device_sync (opt-in)
            prof.start("quorum_wait")
        if scan:
            # consolidated minimal readback: ONE scalar matrix (final
            # step's row; ``accepted`` is cumulative in-program) plus
            # peer_acked — the replay rows are consumed lazily below
            scal = np.asarray(out["scal"])[-1]           # [R, NS]
            res = {k: scal[:, i] for i, k in enumerate(SCAN_KEYS)
                   if k in self.RES_KEYS}
            res["peer_acked"] = np.asarray(out["peer_acked"])[-1]
        elif burst:
            res = {k: np.asarray(getattr(out, k))[-1]
                   for k in self.RES_KEYS if k != "accepted"}
            acc = np.asarray(out.accepted).sum(axis=0)       # [R]
            res["accepted"] = acc
        else:
            res = {k: np.asarray(getattr(out, k))
                   for k in self.RES_KEYS}
            if self._txn and out.txn_vote is not None:
                # serial dispatches only: the txn lane never rides
                # burst/scan programs (their keys stay untouched)
                res["txn_vote"] = np.asarray(out.txn_vote)
        if prof is not None:
            prof.stop("quorum_wait")
        if self._audit:
            # ingest BEFORE _maybe_rebase: the emitted indices are raw
            # (pre-rollover), consistent with the current rebased_total
            if burst or scan:
                # each fused step emitted its own digest window: ingest
                # them in order so the tiling property (no gaps) holds
                get = (out.__getitem__ if scan
                       else lambda k: getattr(out, "commit"
                                              if k == "audit_commit"
                                              else k))
                a_s = np.asarray(get("audit_start"))   # [K, R]
                a_d = np.asarray(get("audit_digest"))  # [K, R, W]
                a_t = np.asarray(get("audit_term"))    # [K, R, W]
                a_c = np.asarray(get("audit_commit"))  # [K, R]
                for k in range(a_s.shape[0]):
                    self._ingest_audit(a_s[k], a_d[k], a_t[k], a_c[k])
                res["audit_start"] = a_s[-1]
                res["audit_digest"] = a_d[-1]
                res["audit_term"] = a_t[-1]
            else:
                for k in ("audit_start", "audit_digest", "audit_term"):
                    res[k] = np.asarray(getattr(out, k))
                self._ingest_audit(res["audit_start"],
                                   res["audit_digest"],
                                   res["audit_term"], res["commit"])
        if self._telemetry:
            # device-truth counters: reduce the dispatch's per-step
            # vectors (sum counters / min headroom over a fused burst),
            # fold into the host accumulator, and export device_*
            # registry series — all on THIS thread, which under the
            # pipelined driver is the readback thread (finish runs
            # there), so telemetry never rides the dispatch path
            from rdma_paxos_tpu.obs import device as _device
            tv = np.asarray(out["telemetry"] if scan
                            else out.telemetry, dtype=np.int64)
            res["telemetry"] = (_device.reduce_steps(tv)
                                if burst or scan else tv)
            _device.accumulate(self.device_counters, res["telemetry"])
            _device.ingest(self.obs, res["telemetry"])
        # ring-full backpressure / deposition: the appended set is a
        # PREFIX of ``taken`` — requeue the remainder in order
        # (submissions to non-leaders are dropped by design)
        txn_notes = []
        with self._host_lock:
            for r in range(self.R):
                take = ticket.taken[r]
                if take and res["role"][r] == int(Role.LEADER):
                    acc_r = int(res["accepted"][r])
                    self._stamp_appends(r, take, acc_r, res)
                    if self.txn is not None and acc_r > 0:
                        txn_notes.append(
                            (0, r, take[:acc_r], int(res["term"][r]),
                             int(res["end"][r]) + self.rebased_total))
                    requeue_shortfall(self.pending[r], take, acc_r)
        # OUTSIDE _host_lock: note_appends takes the coordinator lock,
        # which client threads hold while submitting (coordinator ->
        # cluster order) — calling it from the stamp loop would be the
        # reverse order, an ABBA deadlock against kvs.transact()
        for note in txn_notes:
            self.txn.note_appends(*note)
        if prof is not None:
            prof.start("apply")
        self._replay_committed(
            res, scan_rows=((out["replay_data"], out["replay_meta"],
                             ticket.applied0) if scan else None))
        if prof is not None:
            prof.stop("apply")
        if self._audit:
            self._record_flight(res, ticket.taken, ticket.timeouts,
                                burst_k=ticket.K)
        # the i32 rollover rewrites offsets host-side: it must never
        # run under dispatches still in flight (their outputs carry
        # pre-rollover offsets) — defer until the pipeline drains; the
        # threshold stays crossed, so the draining finish applies it
        with self._host_lock:
            self._tickets.popleft()     # retire: last now covers it
            self.inflight_dispatches -= 1
            if not self._tickets:
                self._maybe_rebase(res)
            self.last = res
        self.step_index += ticket.K
        self._observe_spans(res)
        # read path: renew/revoke leases from this FINISHED step's
        # verified-quorum outputs, then serve due queued reads —
        # between pipelined tickets, never inside one
        if self.leases is not None:
            self.leases.observe(self, res)
        if self.reads is not None:
            self.reads.drain(self)
        if self.streams is not None:
            self.streams.observe(self, res)
        if self.governor is not None:
            self.governor.observe(self, res)
        if self.txn is not None:
            self.txn.observe(self, res)
        if burst or scan:
            B = self.cfg.batch_slots
            self._staging.release(ticket.bufs, [
                ((k, r), min(B, len(t) - k * B))
                for r, t in enumerate(ticket.taken)
                for k in range(-(-len(t) // B) if t else 0)])
        else:
            self._staging.release(ticket.bufs, [
                ((r,), len(t)) for r, t in enumerate(ticket.taken)])
        return res

    def drain(self) -> Optional[Dict[str, np.ndarray]]:
        """Finish every in-flight ticket in order; returns the final
        result (or None when nothing was in flight)."""
        res = None
        while self._tickets:
            res = self.finish(self._tickets[0])
        return res

    def _burst_fn(self, K: int):
        # the "audit" marker is appended ONLY when auditing: default
        # clusters' cache keys are bit-identical to the pre-audit ones
        # (tests/test_audit.py guards exactly this)
        key = (self.cfg, self.R, self._mode, self._use_pallas,
               self._interpret, self._fanout, "burst", K) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ())
        fn = self._STEP_CACHE.get(key)
        if fn is None:
            kw = dict(use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      audit=self._audit, telemetry=self._telemetry)
            if self._mode == "spmd":
                fn = build_spmd_burst(self.cfg, self.R, self.mesh, **kw)
            else:
                fn = build_sim_burst(self.cfg, self.R, **kw)
            self._STEP_CACHE[key] = fn
        return fn

    def _scan_slots(self, K: int) -> int:
        """The scan tier's staged replay width: a K-step scan advances
        commit by at most ``K * batch_slots``, so a small-K dispatch
        never pays the full replay window's extract/transfer (the
        fallback fetch covers a host that fell further behind)."""
        return min(self._replay_W,
                   max(K * self.cfg.batch_slots,
                       self.cfg.window_slots))

    def _scan_fn(self, K: int):
        # the K-window scan tier compiles under its own distinct
        # "scan"-marked cache keys — scan-off clusters' key sets (and
        # programs) are bit-identical to the pre-scan ones, exactly
        # the audit=/telemetry= guard discipline (tests pin it)
        key = (self.cfg, self.R, self._mode, self._use_pallas,
               self._interpret, self._fanout, "scan", K,
               self._scan_slots(K)) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ())
        fn = self._STEP_CACHE.get(key)
        if fn is None:
            kw = dict(replay_slots=self._scan_slots(K),
                      use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      audit=self._audit, telemetry=self._telemetry)
            if self._mode == "spmd":
                fn = build_spmd_scan(self.cfg, self.R, self.mesh, **kw)
            else:
                fn = build_sim_scan(self.cfg, self.R, **kw)
            self._STEP_CACHE[key] = fn
        return fn

    def step_burst(self, max_k: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        """Drain the pending queues through up to ``max(K_TIERS)`` fused
        protocol steps in ONE device dispatch (multi-step driver mode —
        the host-side analog of the reference's busy commit loop). No
        election timeouts fire inside the burst; the caller must only
        burst while a leader is known. Returns the final step's outputs
        (``accepted`` aggregated over the burst). With ``scan=True``
        the dispatch rides the K-window scan tier (same step outputs,
        consolidated readback + in-dispatch replay rows). ``max_k``
        caps the tier at a lower ladder rung (the governor's dial)."""
        require_drained(self._tickets, "step_burst")
        return self.finish(self.begin_burst(max_k=max_k))

    def _build_step(self, *, elections: bool):
        """Compile (or fetch cached) the protocol step for this cluster's
        static config — the single source for both the full and stable
        variants, so they can never drift apart in build flags."""
        key = (self.cfg, self.R, self._mode, self._use_pallas,
               self._interpret, self._fanout, elections) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ()) \
            + (("txn",) if self._txn else ())
        cached = self._STEP_CACHE.get(key)
        if cached is None:
            kw = dict(use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      elections=elections, audit=self._audit,
                      telemetry=self._telemetry, txn=self._txn)
            if self._mode == "spmd":
                cached = build_spmd_step(self.cfg, self.R, self.mesh, **kw)
            else:
                cached = build_sim_step(self.cfg, self.R, **kw)
            self._STEP_CACHE[key] = cached
        return cached

    def prewarm(self, tiers: Optional[Sequence[int]] = None) -> None:
        """Compile every step variant and burst tier up front (on copies
        of the live state — donation would otherwise consume it). A
        first-use JIT pause of seconds mid-serving stalls the whole
        commit pipeline; paying it before traffic starts keeps the
        serving path pause-free."""
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        inp = StepInput(
            batch_data=jnp.zeros((R, B, cfg.slot_words), jnp.int32),
            batch_meta=jnp.zeros((R, B, META_W), jnp.int32),
            batch_count=jnp.zeros((R,), jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=jnp.asarray(self.peer_mask),
            apply_done=jnp.zeros((R,), jnp.int32),
            queue_depth=jnp.zeros((R,), jnp.int32),
            **(dict(txn_watch=jnp.full((R,), -1, jnp.int32),
                    txn_term=jnp.zeros((R,), jnp.int32))
               if self._txn else {}))
        for elections in (True, False):
            fn = self._build_step(elections=elections)
            st = jax.tree.map(lambda x: x.copy(), self.state)
            fn(st, inp)
        pm = jnp.asarray(self.peer_mask)
        ap = jnp.zeros((R,), jnp.int32)
        for K in (tiers if tiers is not None else self.K_TIERS):
            fns = [self._burst_fn(K)]
            if self.scan:
                fns.append(self._scan_fn(K))
            for fn in fns:
                st = jax.tree.map(lambda x: x.copy(), self.state)
                fn(st, jnp.zeros((K, R, B, cfg.slot_words), jnp.int32),
                   jnp.zeros((K, R, B, META_W), jnp.int32),
                   jnp.zeros((K, R), jnp.int32), pm, ap,
                   jnp.zeros((R,), jnp.int32))

    def step(self, timeouts: Sequence[int] = ()) -> Dict[str, np.ndarray]:
        require_drained(self._tickets, "step")
        return self.finish(self.begin_step(timeouts))

    # ------------------------------------------------------------------
    # silent-divergence auditing (obs/audit.py; audit=True clusters)
    # ------------------------------------------------------------------

    def redigest(self, replica: int, lo: int, hi: int) -> int:
        """Range re-digest backfill: recompute the digest chain of
        replica ``replica``'s committed entries ``[lo, hi)`` (raw
        offsets) on device and feed it to the ledger — the repair
        pipeline's coverage-restoration pass. Serial-path only (the
        shared ``require_drained`` rule applies)."""
        return run_redigest(self, self.state.log.buf[replica], lo, hi,
                            group=0, rebased_total=self.rebased_total,
                            replica=replica)

    def _ingest_audit(self, starts, digests, terms, commits) -> None:
        """Feed one step's per-replica digest windows to the ledger,
        converted to ABSOLUTE indices (raw + rebased_total — callers
        run this before _maybe_rebase so the two stay consistent)."""
        led = self.auditor
        led.obs = self.obs              # pick up a late-attached facade
        W = self.cfg.window_slots
        reb = self.rebased_total
        s_l, c_l = starts.tolist(), commits.tolist()
        for r in range(self.R):
            start, commit = s_l[r], c_l[r]
            n = commit - start
            if n <= 0:
                continue
            off = start - (commit - W)
            led.record_window(r, start + reb,
                              digests[r, off:off + n],
                              terms[r, off:off + n], commit + reb,
                              step=self.step_index)

    def _record_flight(self, res, taken, timeouts,
                       burst_k: int = 1) -> None:
        """One flight-recorder entry per dispatch: the step's inputs
        (per-replica submitted batches), scalar outputs, host apply
        cursors, and per-replica digest heads — raw offsets plus the
        rebased_total in force, so the dump is self-describing.
        Values stay numpy arrays / payload bytes (fresh per step,
        copied where a later in-place mutation could reach them); the
        recorder converts to plain JSON data at dump time only."""
        entry = dict(
            step=self.step_index, burst_k=burst_k,
            timeouts=[int(t) for t in timeouts],
            rebased_total=int(self.rebased_total),
            inputs=taken,
            outputs={k: res[k].copy()
                     for k in ("term", "role", "leader_id", "head",
                               "apply", "commit", "end", "accepted")},
            applied=self.applied.copy(),
            digests=dict(start=res["audit_start"].copy(),
                         commit=res["commit"].copy(),
                         window=res["audit_digest"]))
        self.flight.record(entry)

    # ------------------------------------------------------------------
    # span hooks (host-side causal tracing — obs.spans; all no-ops
    # when no recorder is attached or nothing is sampled)
    # ------------------------------------------------------------------

    def _span_recorder(self):
        from rdma_paxos_tpu.obs.spans import active_recorder
        return active_recorder(self.obs)

    def _stamp_appends(self, r: int, take, acc: int, res) -> None:
        """The accepted PREFIX of ``take`` landed at absolute indices
        ``[end-acc, end)`` on leader ``r`` — stamp each sampled span
        with its ``(term, index)`` correlation key."""
        spans = self._span_recorder()
        if spans is None or not spans.open_count or acc <= 0:
            return
        end_abs = int(res["end"][r]) + self.rebased_total
        term = int(res["term"][r])
        replicas = range(self.R)
        for i, (_t, conn, req, _p) in enumerate(take[:acc]):
            spans.stamp_append(conn, req, term, end_abs - acc + i, r,
                               replicas=replicas)

    def _observe_spans(self, res) -> None:
        """Advance every replica's commit/apply span frontiers (absolute,
        rebase-corrected — runs after ``_maybe_rebase`` so the offsets
        and ``rebased_total`` are mutually consistent)."""
        spans = self._span_recorder()
        if spans is None or not spans.open_count:
            return
        rebased = self.rebased_total
        for r in range(self.R):
            spans.commit_advance(r, int(res["commit"][r]) + rebased)
            spans.apply_advance(r, int(self.applied[r]) + rebased)

    # consecutive post-threshold zero-delta steps before the stall is
    # declared — shared with NodeDaemon (config.REBASE_STALL_STEPS)
    REBASE_STALL_STEPS = REBASE_STALL_STEPS

    def _rebase_stalled_step(self, res) -> None:
        """One post-threshold step passed with the rollover delta
        pinned at 0 — count it, and surface the stall once it persists
        (the i32 ceiling is approaching and nothing will fire)."""
        self.rebase_stall_steps += 1
        if self.rebase_stall_steps < self.REBASE_STALL_STEPS:
            return
        self.rebase_stalled += 1
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("rebase_stalled")
            if self.rebase_stall_steps == self.REBASE_STALL_STEPS:
                heads = [int(res["head"][r]) for r in range(self.R)]
                self.obs.trace.record(
                    _trace.REBASE_STALLED,
                    end_max=int(res["end"].max()),
                    threshold=self.cfg.rebase_threshold,
                    min_head=min(heads), heads=heads,
                    steps=self.rebase_stall_steps)

    # holds-lock: _host_lock
    def _maybe_rebase(self, res) -> None:
        """Coordinated i32-offset rollover (LogConfig.rebase_threshold):
        when any end offset crosses the threshold, subtract the minimum
        head from EVERY offset on every replica and from the host apply
        cursors — invisible to the protocol (offsets are relative), and
        it restores ~threshold entries of headroom. The in-process
        driver is omniscient, so the min is over ALL replicas (not just
        heard ones) — partition-safe: a partitioned laggard's low head
        simply defers the rollover until it recovers or is evicted.
        ``res`` is adjusted in place so callers observe post-rollover
        offsets."""
        if int(res["end"].max()) < self.cfg.rebase_threshold:
            return
        # the slot of global index g is g % n_slots and entries do NOT
        # move: the subtraction must preserve the mapping, so the delta
        # is the min head rounded DOWN to a multiple of n_slots. A
        # replica already flagged need_recovery is EXCLUDED from the
        # min: it stopped replaying (snapshot install renumbers it from
        # the donor), and letting its frozen head pin the rollover
        # would wedge the whole cluster at the i32 ceiling. Its offsets
        # may go transiently negative — benign: the gap gate keeps it
        # from absorbing windows until recovery overwrites them.
        heads = [int(res["head"][r]) for r in range(self.R)
                 if r not in self.need_recovery]
        delta = rebase_delta_of(heads, self.cfg.n_slots)
        if delta <= 0:
            self._rebase_stalled_step(res)
            return
        from rdma_paxos_tpu.consensus.snapshot import rebase_offsets
        self.state = rebase_offsets(self.state, delta)
        self.applied -= delta
        for k in ("head", "apply", "commit", "end"):
            res[k] = res[k] - delta
        # keep the returned dict self-consistent: audit_start is an
        # index too (the ledger already ingested pre-rollover)
        if "audit_start" in res:
            res["audit_start"] = res["audit_start"] - delta
        self.rebases += 1
        self.rebased_total += delta
        self.rebase_stall_steps = 0          # re-arm stall detection
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("rebases_total")
            self.obs.metrics.inc("rebased_entries_total", delta)
            self.obs.trace.record(_trace.REBASE_APPLIED, delta=delta,
                                  rebases=self.rebases)

    def _replay_committed(self, res, scan_rows=None) -> None:
        """Host apply loop: fetch newly committed entries from the device
        log and 'replay' them (tests record them; the real driver hands
        them to the proxy) — apply_committed_entries analog
        (dare_server.c:1815-1974). All replicas' windows ride ONE device
        dispatch per sweep.

        ``scan_rows`` (the K-window scan tier): ``(wd_fut, wm_fut,
        applied0)`` replay rows that rode the scan dispatch itself,
        starting at the pre-dispatch apply cursors — consumed FIRST, so
        a scan step whose commit delta fits the staged window pays
        ZERO standalone fetch dispatches; any remainder falls through
        to the fetch loop below (identical decode → identical
        streams)."""
        W = self._replay_W
        if scan_rows is not None:
            wd_fut, wm_fut, applied0 = scan_rows
            staged = int(wm_fut.shape[-2])     # K-sized, <= replay_W
            wd_all = wm_all = None
            for r in range(self.R):
                if (r in self._wedged or r in self.need_recovery):
                    continue
                commit = int(res["commit"][r])
                off = int(self.applied[r]) - int(applied0[r])
                n = int(min(commit - self.applied[r], staged - off))
                if n <= 0 or off < 0:
                    continue
                if wd_all is None:      # lazy: transfer only if used
                    wd_all = np.asarray(wd_fut)
                    wm_all = np.asarray(wm_fut)
                wd = wd_all[r, off:off + n]
                wm = wm_all[r, off:off + n]
                if int(wm[0, M_GIDX]) != self.applied[r]:
                    self.need_recovery.add(r)       # slot recycled
                    continue
                decode_window(wm, wd, n, self.replayed[r],
                              self.frames[r], self.collect_frames,
                              rebase=self.rebased_total)
                self.applied[r] += n
        # Force-pruned laggards: when the ring no longer PHYSICALLY holds
        # entry `applied` (a newer entry recycled its slot — possible
        # once forced pruning let appends run ahead of a wedged member's
        # apply), replaying would feed garbage to the app. The stamped
        # global index (M_GIDX) proves integrity: fetched-entry gidx ==
        # expected index, else flag for snapshot recovery and stop.
        # Being merely below `head` is NOT sufficient to flag — the
        # benign one-step lazy-push lag puts followers there routinely
        # while their slots are still intact.
        while True:
            todo = [r for r in range(self.R)
                    if r not in self._wedged
                    and r not in self.need_recovery
                    and self.applied[r] < int(res["commit"][r])]
            if not todo:
                return
            starts = jnp.asarray(self.applied.astype(np.int32))
            # bind the fetch's log argument UNDER the host lock: the
            # pipelined dispatch thread donates the current state
            # buffers into the next step's dispatch, and a fetch bound
            # after that donation reads deleted buffers. Binding first
            # is sufficient — the runtime keeps an argument buffer
            # alive for an already-enqueued program — and the newer log
            # is safe to read: committed entries are immutable, the
            # rollover is deferred while tickets are in flight, and the
            # M_GIDX integrity check still guards slot recycling. Only
            # the BIND holds the lock; the blocking result read below
            # runs outside it so the dispatch path never stalls.
            with self._host_lock:
                wd_fut, wm_fut = self._fetch_all(self.state.log, starts)
            wd_all, wm_all = np.asarray(wd_fut), np.asarray(wm_fut)
            for r in todo:
                commit = int(res["commit"][r])
                n = int(min(commit - self.applied[r], W))
                wd, wm = wd_all[r], wm_all[r]
                if n > 0 and int(wm[0, M_GIDX]) != self.applied[r]:
                    self.need_recovery.add(r)       # slot recycled
                    continue
                decode_window(wm, wd, n, self.replayed[r],
                              self.frames[r], self.collect_frames,
                              rebase=self.rebased_total)
                self.applied[r] += n

    # ---------------- inspection ----------------

    def leader(self) -> int:
        assert self.last is not None
        ids = [r for r in range(self.R)
               if self.last["role"][r] == int(Role.LEADER)]
        return ids[0] if len(ids) == 1 else -1

    def run_until_elected(self, candidate: int, max_steps: int = 5) -> int:
        for _ in range(max_steps):
            res = self.step(timeouts=[candidate])
            if res["role"][candidate] == int(Role.LEADER):
                return candidate
        raise AssertionError("election did not converge")
