"""ShardedClusterDriver — the e2e data plane over G consensus groups.

``ClusterDriver`` serves one consensus group: every client session rides
the single leader. This driver serves a :class:`~rdma_paxos_tpu.shard.
cluster.ShardedCluster` through the SAME polling/pipelining loop — the
multi-group scaling ``benchmarks/shard_bench.py`` demonstrates in sim,
threaded through the real proxy/shim/app path:

  * **Every replica is a serving front-end.** Clients connect to any
    replica's app; the shim events flow into that replica's proxy as
    usual. There is no single cluster leader — each of the G groups
    elects its own, spread across the R replicas.
  * **Connections are routed by key prefix.** A shim connection is
    pinned to the consensus group that owns the KEY PREFIX of its first
    replicated SEND (``KeyRouter.group_of``; the prefix is the key up
    to the first ``-``/``:``/``.`` delimiter — RESP arrays and inline
    commands both parse). All of the connection's traffic then rides
    that one group's log, so per-key linearizability holds as long as
    clients keep a connection's keys within one routing unit — the
    same client contract as Redis Cluster hash slots.
  * **CONNECT is held, not blocked.** The group is unknown until the
    first SEND names a key, so the CONNECT entry is held and acked
    immediately (it carries no data); when the first SEND pins group g
    the CONNECT is submitted ahead of it into g's log — FIFO within
    the group, so every replica replays CONNECT before the data, and
    an acked SEND transitively proves its CONNECT committed.
  * **Acks demux per group.** Commit waiters are tracked per
    ``(replica, group)`` FIFO; group g's commit stream releases only
    g's waiters, so groups committing at different rates can never
    reorder or cross-release acks.

The pipelined dispatch loop (double-buffered ``begin_*``/``finish``,
readback thread) is inherited unchanged — the engines share one
ticket contract. Operator surfaces that are single-group by design
(membership change, snapshot recovery, app checkpoints, step-down
detection) are not supported in sharded mode and raise; ROADMAP item 4
(elastic resharding) is where they return.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.health import make_snapshot
from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_S
from rdma_paxos_tpu.obs.spans import span_trace_id
from rdma_paxos_tpu.obs.tracectx import health_blame as _health_blame
from rdma_paxos_tpu.proxy.proxy import PendingEvent
from rdma_paxos_tpu.runtime.driver import ClusterDriver, conn_origin
from rdma_paxos_tpu.runtime.hostpath import plan_segment
from rdma_paxos_tpu.runtime.timers import GroupStepTimer
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.router import KeyRouter
from rdma_paxos_tpu.utils.codec import fragment

PREFIX_DELIMS = (b"-", b":", b".")


def key_prefix_of(payload: bytes) -> bytes:
    """The routing key prefix of a replicated SEND payload: the first
    command's key, truncated at the first prefix delimiter. Parses both
    RESP arrays (``*3\\r\\n$3\\r\\nSET\\r\\n$5\\r\\nkey-1...``) and
    inline/space-separated commands (``SET key-1 v1``). A payload with
    no recognizable key routes by the empty prefix (a legal router
    input) — deterministic, just unspread."""
    key = b""
    if payload[:1] == b"*":
        parts = payload.split(b"\r\n", 5)
        if len(parts) >= 5:
            key = parts[4]
    else:
        toks = payload.split(None, 2)
        if len(toks) >= 2:
            key = toks[1]
        elif toks:
            key = toks[0]
    # truncate at the FIRST-occurring delimiter (not the first in
    # PREFIX_DELIMS order): b"user.1-x" routes as b"user", never
    # b"user.1" — anything else would split a documented routing unit
    cut = len(key)
    for d in PREFIX_DELIMS:
        i = key.find(d, 0, cut)
        if i > 0:
            cut = i
    return key[:cut]


class ShardedClusterDriver(ClusterDriver):
    """One polling loop serving G consensus groups end to end."""

    def __init__(self, cfg: LogConfig, n_replicas: int, n_groups: int,
                 *, router: Optional[KeyRouter] = None,
                 key_of=key_prefix_of, mesh=None,
                 group_timer_lo: int = 6, group_timer_hi: int = 12,
                 **kw):
        if kw.get("link_model") is not None:
            raise ValueError(
                "sharded driver: attach per-group link models via "
                "cluster.link_models[g], not link_model=")
        self.G = int(n_groups)
        self._router = (router if router is not None
                        else KeyRouter(self.G))
        self._key_of = key_of
        # mesh=(group_shards, R) or a prebuilt 2-D Mesh routes the
        # engine onto the multi-chip (group, replica) layout; the
        # driver's pipelined loop is engine-agnostic (same ticket
        # contract), so nothing else changes
        self._mesh = mesh
        # per-group leader views (the sharded analog of _leader_view;
        # _leader_view itself becomes the ALL-GROUPS-LED aggregate so
        # leader()-polling boot code works unchanged)
        # guarded-by: _lock [writes]
        self._group_views: List[int] = [-1] * self.G
        # guarded-by: _lock
        self._conn_group: Dict[int, int] = {}    # conn -> pinned group
        # guarded-by: _lock
        self._conn_hold: Dict[int, tuple] = {}   # conn -> held CONNECT
        super().__init__(cfg, n_replicas, **kw)
        # (replica, group) commit-waiter FIFOs + replay cursors — the
        # single-group driver's rt.inflight / rt.replay_cursor, demuxed
        # guarded-by: _lock
        self._inflight_g: List[List[collections.deque]] = [
            [collections.deque() for _ in range(self.G)]
            for _ in range(n_replicas)]
        # guarded-by: _lock
        self._replay_cursor = [[0] * self.G for _ in range(n_replicas)]
        # per-group jittered STEP-DOMAIN election timers + candidate
        # rotation (group g's first candidate is replica g % R, so
        # converged leaderships land round-robin without any explicit
        # place_leaders choreography). Deterministic per-(seed, group)
        # periods: a chaos replay of the same step sequence redraws
        # identical timings — bit-reproducible, unlike wall clocks.
        seed = kw.get("seed", 0)
        self._gtimers = [GroupStepTimer(g, seed=seed,
                                        lo=group_timer_lo,
                                        hi=group_timer_hi)
                         for g in range(self.G)]
        self._elect_round = [0] * self.G
        # elastic-topology cutover hook: the controller calls this on
        # the driver thread right after the atomic router swap
        self.cluster._on_topology_cutover = self._on_topology_cutover

    def _make_cluster(self, cfg, n_replicas, group_size, mode, fanout,
                      audit, telemetry, txn=False):
        return ShardedCluster(cfg, n_replicas, self.G,
                              router=self._router, fanout=fanout,
                              group_size=group_size, audit=audit,
                              mesh=self._mesh, telemetry=telemetry,
                              scan=self._scan, txn=txn)

    def _wire_repair(self) -> None:
        """Sharded driver: repair uses the controller's ENGINE-level
        digest-verified install (per-group snapshot + backfill — one
        group's repair never stalls the others); the driver only
        resyncs its per-(replica, group) replay cursor. Store/app
        rebuild for a repaired front-end rides ROADMAP item 4
        (elastic resharding) — the repaired replica's consensus state
        and audit coverage are fully restored here."""
        self.repair.post_install = self._repair_post_install
        self.repair.on_quarantine = self._repair_on_quarantine

    def _repair_post_install(self, g: int, r: int, donor: int) -> None:
        with self._lock:
            self._replay_cursor[r][g] = len(self.cluster.replayed[g][r])

    def _repair_on_quarantine(self, g: int, r: int) -> None:
        """A front-end just entered quarantine for group ``g``: its
        replay/apply stream for that group is frozen, so its blocked
        commit waiters can never be ack-released — fail them now so
        clients retry against a healthy front-end (invoked by the
        controller OUTSIDE its lock)."""
        releases = []
        with self._lock:
            dq = self._inflight_g[r][g]
            n = len(dq)
            while dq:
                ev, _ = dq.popleft()
                releases.append(ev)
        for ev in releases:
            ev.release(-1)
        if releases:
            self.obs.metrics.inc("inflight_failed_total", len(releases),
                                 replica=r)
            self.obs.trace.record(obs_trace.INFLIGHT_FAILED,
                                  replica=r, group=g, count=len(releases),
                                  site="repair quarantine")
            self.obs.spans.fail_open(self._span_rep(g, r))

    def _span_rep(self, g: int, r: int) -> int:
        """Span-track replica id in the ENGINE's group namespace —
        delegated to the cluster so driver-side enqueue/ack/fail
        events land on the same per-group tracks as the engine's
        append/commit/apply stamps and the ``(group, term, index)``
        correlation closes end to end."""
        return self.cluster._span_rep(g, r)

    @property
    def router(self) -> KeyRouter:
        return self._router

    def leaders(self) -> List[int]:
        with self._lock:
            return list(self._group_views)

    # ------------------------------------------------------------------
    # intake: key-prefix routing (see module docstring)
    # ------------------------------------------------------------------

    def _accepts_clients(self, r: int) -> bool:
        # every replica fronts the cluster while any group is led (the
        # per-group availability check happens at SEND routing time) —
        # EXCEPT a replica the repair pipeline holds in any group: its
        # replay for the held group is frozen, so sessions it admits
        # could stall forever on ack release
        if (self.repair is not None
                and self.repair.serving_blocked_any(r)):
            return False
        return any(v >= 0 for v in self._group_views)

    def _enqueue_locked(self, r: int, rt, etype: int, conn_id: int,
                        payload: bytes):
        if etype == int(EntryType.CONNECT):
            # held until the first SEND names a key; acked immediately
            # (carries no data — an acked SEND later transitively
            # proves the CONNECT committed, FIFO within its group)
            self._conn_hold[conn_id] = payload
            self.obs.metrics.inc("proxy_events_total", replica=r)
            return 0
        g = self._conn_group.get(conn_id)
        if g is None and etype == int(EntryType.CLOSE):
            # nothing of this conn ever replicated
            self._conn_hold.pop(conn_id, None)
            return 0
        if g is None:
            g = self._router.group_of(self._key_of(payload))
            self._conn_group[conn_id] = g
        if self._group_views[g] < 0:
            # the routed group is (transiently) leaderless: fail fast
            # so the client retries — a commit wait could stall for a
            # whole election otherwise
            rt.replicated_conns.discard(conn_id)
            self._conn_group.pop(conn_id, None)
            self._conn_hold.pop(conn_id, None)
            self.obs.metrics.inc("events_refused_total", replica=r)
            return -1
        rows = []
        held = self._conn_hold.pop(conn_id, None)
        if held is not None:
            rt.submit_seq += 1
            rows.append((g, int(EntryType.CONNECT), conn_id, held,
                         rt.submit_seq))
        frags = (fragment(payload, self.cfg.slot_bytes)
                 if etype == int(EntryType.SEND) else [payload])
        ev = PendingEvent(EntryType(etype), conn_id, payload)
        for f in frags:
            rt.submit_seq += 1
            rows.append((g, etype, conn_id, f, rt.submit_seq))
        if etype == int(EntryType.CLOSE):
            self._conn_group.pop(conn_id, None)
        self._submitq[r].extend(rows)
        self._inflight_g[r][g].append((ev, rt.submit_seq))
        self.obs.metrics.inc("proxy_events_total", replica=r)
        self.obs.trace.record(obs_trace.PROXY_ENQUEUE, replica=r,
                              etype=etype, conn=conn_id, group=g,
                              frags=len(frags),
                              submit_seq=rt.submit_seq)
        # causal span birth keyed (conn, final fragment seq) — the
        # pair the per-group ack release matches on; the origin track
        # is the GROUP-NAMESPACED front-end replica, so the engine's
        # (group, term, index)-stamped append/commit/apply marks
        # correlate onto it
        self.obs.spans.begin(conn_id, rt.submit_seq,
                             self._span_rep(g, r))
        self._wake.set()
        return ev

    def _pump_submitq(self) -> None:
        with self._lock, self.cluster._host_lock:
            views = self._group_views
            for r in range(self.R):
                if not self._submitq[r]:
                    continue
                # demux the intake batch per group, then ONE locked
                # extend per (group, leader) — batched intake, no
                # per-entry Python. The group's CURRENT leader takes
                # the append; if leadership vanished since enqueue the
                # rows land on a non-leader and are dropped by design
                # — the leadership-change sweep fails their waiters
                per_g: Dict[int, list] = {}
                for g, etype, conn, frag, seq in self._submitq[r]:
                    per_g.setdefault(g, []).append(
                        (etype, conn, seq, frag))
                for g, rows in per_g.items():
                    q = views[g] if views[g] >= 0 else 0
                    self.cluster.submit_many(g, q, rows)
                self._submitq[r].clear()

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def _backlog(self) -> int:
        return max(len(q) for row in self.cluster.pending for q in row)

    # holds-lock: _lock
    def _waiter_count(self) -> int:
        return sum(len(dq) for row in self._inflight_g for dq in row)

    def _busy(self) -> bool:
        # checked OUTSIDE self._lock: the topology cutover hook runs
        # with the controller's lock held and takes self._lock
        # (topology._lock -> driver._lock); nesting the reverse order
        # here would deadlock
        topo = getattr(self.cluster, "topology", None)
        if topo is not None and (topo.needs_drain() or topo.cooling()):
            return True     # keep stepping so the window's records
            # land and the bounded post-window cooldown expires
        with self._lock:
            return bool(any(self._submitq) or self._backlog()
                        or self._waiter_count()
                        or (self.cluster.reads is not None
                            and self.cluster.reads.pending_count())
                        # in-flight transactions decide off the
                        # finish() tail — keep stepping until then
                        or (self.cluster.txn is not None
                            and self.cluster.txn.wants_serial()))

    def step(self) -> Dict:
        """One host-loop iteration: elections for leaderless groups
        ride the same dispatch as every other group's step; any
        backlog rides a fused all-groups burst."""
        self._drain_admin()
        self._pump_submitq()
        c = self.cluster
        timeouts: Dict[int, list] = {}
        if c.last is not None:
            for g in range(self.G):
                if self._group_views[g] >= 0:
                    continue
                # leaderless groups tick their step-domain timer once
                # per poll iteration; a firing targets the rotation's
                # next candidate (start at g % R — the round-robin
                # spread place_leaders used to script explicitly).
                # Replicas the repair pipeline holds (quarantine /
                # probation) are skipped — a quarantined candidate is
                # cut from the hear-matrix and can never win anyway,
                # and a probation replica must not lead while its
                # clean-step hysteresis runs.
                if self._gtimers[g].tick():
                    cand = -1
                    for _ in range(self.R):
                        cc = (g + self._elect_round[g]) % self.R
                        self._elect_round[g] += 1
                        if not self._repair_blocked(cc, g):
                            cand = cc
                            break
                    if cand < 0:
                        continue        # every replica held — escalated
                    timeouts[g] = [cand]
                    self.obs.metrics.inc("election_timeouts_total",
                                         group=g)
        # governed tier: per-GROUP rung decisions share one dispatch,
        # so the program-level cap is the max rung (dec.max_k); a
        # serial decision routes through the all-groups single step
        dec = (self.governor.decision if self.governor is not None
               else None)
        if (not timeouts and c.last is not None
                and all(v >= 0 for v in self._group_views)
                and self._backlog()
                and not (c.txn is not None and c.txn.wants_serial())
                and (dec is None or dec.max_k > 1)):
            self._timer_obs.start("device_step")
            res = c.step_burst(max_k=dec.max_k if dec is not None
                               else None)
            self._timer_obs.stop("device_step")
        else:
            self._timer_obs.start("device_step")
            res = c.step(timeouts=timeouts)
            self._timer_obs.stop("device_step")
        return self._post_step(res)

    def _pipeline_ready(self) -> bool:
        c = self.cluster
        if c.last is None:
            return False
        if any(v < 0 for v in self._group_views):
            return False
        if c.need_recovery:
            return False
        # a due repair needs one drained serial iteration (per-group
        # surgery); depth-D pipelining re-engages right after
        if self.repair is not None and self.repair.needs_drain():
            return False
        if int(c.last["end"].max()) >= self.cfg.rebase_threshold:
            return False
        # an in-flight transaction holds the commit lane: votes and
        # decision records ride SERIAL dispatches (the same give-way
        # rule elections and repair follow)
        if c.txn is not None and c.txn.wants_serial():
            return False
        # an open topology transition window holds the serial path
        # (checked before self._lock — see _busy's lock-order note)
        topo = getattr(c, "topology", None)
        if topo is not None and topo.needs_drain():
            return False
        # the governor engages/disengages pipelining (see
        # ClusterDriver._pipeline_ready)
        if (self.governor is not None
                and not self.governor.decision.pipeline):
            return False
        # append batches only — see ClusterDriver._pipeline_ready
        with self._lock:
            return bool(any(self._submitq) or self._backlog())

    def _idle_margin(self) -> float:
        """The sharded election timers are STEP-DOMAIN (GroupStepTimer
        ticks once per poll iteration, and only for leaderless
        groups); the idle-skip gate already requires every group led
        (``_leader_view >= 0``), so no timer can fire while parked —
        the margin is unbounded and the backoff cap alone paces the
        heartbeat."""
        return float("inf")

    def _repair_held_any(self) -> bool:
        return any(self.repair.blocked_replicas(g)
                   for g in range(self.G))

    def _update_leader_view(self, res) -> None:
        views = []
        for g in range(self.G):
            # a repair-held replica's self-claim is not a serving
            # leadership: treating its group as leaderless fails the
            # waiters (clients retry) and lets the group timer elect a
            # healthy replacement instead of pinning the stale view
            claims = [(int(res["term"][g, r]), r)
                      for r in range(self.R)
                      if int(res["role"][g, r]) == int(Role.LEADER)
                      and not self._repair_blocked(r, g)]
            views.append(max(claims)[1] if claims else -1)
        with self._lock:
            prev = self._group_views
            self._group_views = views
            self._leader_view = (0 if all(v >= 0 for v in views)
                                 else -1)
        for g in range(self.G):
            if views[g] != prev[g] or views[g] < 0:
                # leadership moved or vanished: entries submitted to
                # the old leader may never commit — fail g's blocked
                # waiters so clients retry (late commits are harmless:
                # acks match by stamped seq, and released events are
                # terminal)
                self._fail_group_inflight(g, "leadership change")

    def _fail_group_inflight(self, g: int, site: str) -> None:
        with self._lock:
            for r in range(self.R):
                dq = self._inflight_g[r][g]
                n = len(dq)
                if not n:
                    continue
                rt = self.runtimes[r]
                if (rt.proxy is not None and rt.proxy.spec_mode
                        and not rt.app_dirty):
                    rt.app_dirty = True
                    rt.log.info_wtime(
                        "APP DIRTY: %d speculated events failed at %s "
                        "(group %d)" % (n, site, g))
                while dq:
                    ev, _ = dq.popleft()
                    ev.release(-1)
                self.obs.metrics.inc("inflight_failed_total", n,
                                     replica=r)
                self.obs.trace.record(obs_trace.INFLIGHT_FAILED,
                                      replica=r, group=g, count=n,
                                      site=site)
                # terminal failover status on the failed waiters'
                # spans (group-namespaced track) — never leaked
                self.obs.spans.fail_open(self._span_rep(g, r))

    def _on_topology_cutover(self, donors, targets) -> None:
        """An elastic cutover just swapped the live router: some keys
        moved OFF every group in ``donors``. Their blocked commit
        waiters are failed (clients retry and re-resolve the owner —
        same contract as a leadership change) and proxy conn->group
        pins on donor groups are dropped so the next SEND re-routes
        under the new map. Held CONNECTs stay held: they carry no key
        and route with their first SEND. Invoked by the topology
        controller (its lock held) on the driver thread — we take
        self._lock here, fixing the topology._lock -> driver._lock
        order the _busy/_pipeline_ready gates respect by checking
        ``needs_drain()`` OUTSIDE self._lock."""
        for g in donors:
            self._fail_group_inflight(g, "topology cutover")
        with self._lock:
            stale = [c for c, g in self._conn_group.items()
                     if g in donors]
            for c in stale:
                del self._conn_group[c]

    def _fail_inflight_locked(self, rt, site: str) -> None:
        """Fail EVERY group's blocked waiters on this replica (caller
        holds ``_lock``) — crash/stop paths."""
        n = sum(len(dq) for dq in self._inflight_g[rt.idx])
        if (n and rt.proxy is not None and rt.proxy.spec_mode
                and not rt.app_dirty):
            rt.app_dirty = True
            rt.log.info_wtime(
                "APP DIRTY: %d speculated events failed at %s"
                % (n, site))
        for g, dq in enumerate(self._inflight_g[rt.idx]):
            while dq:
                ev, _ = dq.popleft()
                ev.release(-1)
            self.obs.spans.fail_open(self._span_rep(g, rt.idx))
        if n:
            self.obs.metrics.inc("inflight_failed_total", n,
                                 replica=rt.idx)
            self.obs.trace.record(obs_trace.INFLIGHT_FAILED,
                                  replica=rt.idx, count=n, site=site)

    def _post_step(self, res) -> Dict:
        self._update_leader_view(res)
        for g in range(self.G):
            if self._group_views[g] >= 0:
                self._gtimers[g].beat()
        for r, rt in enumerate(self.runtimes):
            self._apply_new_entries(r, rt)
        # self-healing observation (same contract as the base driver's
        # _post_step): quarantine new findings / advance probation on
        # every finished step — the surgery itself waits for a drained
        # serial iteration (_drain_admin → repair.drive)
        if self.repair is not None:
            self.repair.observe()
        self._observe_step(res)
        return res

    # ------------------------------------------------------------------
    # apply / ack release (per group)
    # ------------------------------------------------------------------

    def _apply_new_entries(self, r: int, rt) -> None:
        c = self.cluster
        progressed = False
        releases: list = []
        sampled: set = set()      # (conn, req) span keys acked now
        replaying = rt.replay is not None and not rt.app_dirty

        def own_of(conns, _gens):
            return conn_origin(conns) == r

        self._phase_prof.start("apply_replay_ack")
        for g in range(self.G):
            stream = c.replayed[g][r]
            n = len(stream)
            cur = self._replay_cursor[r][g]
            if cur >= n:
                continue
            # columnar batch consumption — Python O(1) per decoded
            # window (see ClusterDriver._apply_new_entries)
            segs = (stream.segments_from(cur)
                    if hasattr(stream, "segments_from")
                    else [stream[cur:]])
            self._replay_cursor[r][g] = n
            progressed = True
            if rt.store is not None:
                blobs = c.frames[g][r]
                if blobs:
                    c.frames[g][r] = []
                    for b in blobs:
                        rt.store.append_framed(b)
            own_max = -1
            for seg in segs:
                seg_max, ops, _n_rem = plan_segment(
                    seg, own_of, want_ops=replaying)
                own_max = max(own_max, seg_max)
                if replaying:
                    for etype, conn, payload in ops:
                        rt.replay.apply(etype, conn, payload)
            if own_max >= 0:
                self._phase_prof.start("ack_release")
                with self._lock:
                    dq = self._inflight_g[r][g]
                    while dq and dq[0][1] <= own_max:
                        ev, seq = dq.popleft()
                        releases.append((ev, seq))
                # span acks live on the GROUP-NAMESPACED track the
                # enqueue-side begin() used — (group, term, index)
                # correlation closes here; sampled keys feed the
                # latency histogram's exemplars below
                sampled.update(
                    self.obs.spans.ack_release(self._span_rep(g, r),
                                               own_max))
                self._phase_prof.stop("ack_release")
        self._phase_prof.stop("apply_replay_ack")
        if progressed and replaying:
            rt.replay.drain_responses()
        if progressed and rt.store is not None:
            now = time.monotonic()
            if now - rt.last_sync > self.sync_period:
                rt.store.sync()
                rt.last_sync = now
        if releases:
            acked = {req: conn for conn, req in sampled}
            now = time.perf_counter()
            for ev, seq in releases:
                ev.release(0)
                self.obs.metrics.observe(
                    "commit_latency_seconds", now - ev.t0,
                    buckets=LATENCY_BUCKETS_S,
                    exemplar=(span_trace_id(acked[seq], seq)
                              if seq in acked else None),
                    replica=r)
            self.obs.trace.record(obs_trace.PROXY_ACK_RELEASE,
                                  replica=r, count=len(releases))

    # ------------------------------------------------------------------
    # observability / health
    # ------------------------------------------------------------------

    def _observe_step(self, res) -> None:
        m = self.obs.metrics
        for r in range(self.R):
            m.set("inflight_waiters",
                  sum(len(dq) for dq in self._inflight_g[r]),
                  replica=r)
        m.set("cluster_leader", self._leader_view)
        self._cadence_observe()

    def _health_snapshots(self, res) -> Dict[int, Dict]:
        snaps = {}
        for r in range(self.R):
            rt = self.runtimes[r]
            snaps[r] = make_snapshot(
                replica=r,
                groups_led=[g for g in range(self.G)
                            if self._group_views[g] == r],
                inflight=sum(len(dq) for dq in self._inflight_g[r]),
                app_dirty=rt.app_dirty,
                store=(rt.store.stats() if rt.store is not None
                       else None))
        return snaps

    def health(self) -> Dict:
        """Sharded cluster health, conforming to the same
        ``obs.health.CLUSTER_HEALTH_FIELDS`` schema as the
        single-group driver's (``leaders`` stands in for
        ``leader``)."""
        from rdma_paxos_tpu.obs.health import make_cluster_snapshot
        h = self.cluster.health()
        h.pop("schema", None)     # the wrapper stamps the schema
        h.update(
            leaders=self.leaders(),
            all_groups_led=self.leader() >= 0,
            replicas=[snap for _, snap in
                      sorted(self._health_snapshots(None).items())],
            loop_error=(repr(self.loop_error) if self.loop_error
                        else None),
            alerts=self.alerts.state(),
            audit_artifact=self.audit_artifact,
            repair=(self.repair.status()
                    if self.repair is not None else None),
            reads=(self.cluster.reads.status()
                   if self.cluster.reads is not None else None),
            streams=(self.cluster.streams.status()
                     if self.cluster.streams is not None else None),
            governor=(self.governor.status()
                      if self.governor is not None else None),
            txn=(self.cluster.txn.health()
                 if self.cluster.txn is not None else None),
            blame=_health_blame(self.obs))
        return make_cluster_snapshot(**h)

    def read(self, fn=None, *, key=None, group: Optional[int] = None,
             replica: Optional[int] = None, timeout: float = 30.0):
        """Queue one linearizable read against the group owning
        ``key`` (or an explicit ``group``). The serving replica
        defaults to that group's lease holder — which
        ``place_leaders`` spreads across the R replicas, so read load
        fans out instead of piling onto one front-end. Same hub
        contract as the single-group driver: served on the readback
        thread between pipelined tickets, never through the log."""
        if group is None:
            if key is None:
                raise ValueError("read needs key= or group=")
            group = self._router.group_of(key)
        if replica is None:
            replica = self.read_replica(group)
        return super().read(fn, replica=replica, group=group,
                            timeout=timeout)

    def read_replica(self, group: int = 0) -> int:
        lm = self.cluster.leases
        r = lm.serving_holder(group) if lm is not None else -1
        if r < 0:
            with self._lock:
                r = self._group_views[group]
        return r if r >= 0 else 0

    def can_serve_read(self, r: int) -> bool:
        """True iff replica ``r`` verified its leadership on the latest
        step for EVERY group it leads (and leads at least one)."""
        last = self.cluster.last
        if last is None:
            return False
        led = [g for g in range(self.G) if self._group_views[g] == r]
        return bool(led) and all(
            bool(last["leadership_verified"][g, r]) for g in led)

    # ------------------------------------------------------------------
    # unsupported single-group operator surfaces
    # ------------------------------------------------------------------

    def request_membership(self, new_mask: int) -> None:
        raise NotImplementedError(
            "membership changes are single-group only (ROADMAP: "
            "elastic resharding)")

    def recover_replica(self, r, donor=None, timeout: float = 60.0):
        raise NotImplementedError(
            "snapshot recovery is single-group only")

    def reset_app(self, r: int, timeout: float = 60.0) -> None:
        raise NotImplementedError("app reset is single-group only")

    def checkpoint_app(self, r: int, timeout: float = 60.0) -> None:
        raise NotImplementedError(
            "app checkpoints are single-group only")
