"""Adaptive dispatch governor — telemetry-driven auto-tuning of the
dispatch geometry.

Every performance lever this repo grew — burst K (PR 4), depth-D
pipelining (PR 6), the K-window scan tier (PR 13) — is a static flag a
human picks per bench run. Real traffic is bursty, diurnal, and
read/write mixed, so a hand-picked geometry is always wrong for part
of the day: a deep burst tier pays window-fill latency at trickle
load, a serial geometry caps throughput at peak, and an idle cluster
still pays full-rate poll dispatches (the PR 8 measurement: idle
dispatches bias overhead rows by 10+ points). APUS wins by amortizing
— fewer, larger protocol rounds once per-round cost is fixed — which
only holds when the batching degree TRACKS offered load.

:class:`DispatchGovernor` is a step-domain feedback controller that
closes that loop. It runs on the existing readback thread (the
engines' ``finish()`` observes it exactly like ``leases``/``reads``)
and publishes one :class:`Decision` per finished step:

* **tier** — serial step vs fused burst/scan ``K``, chosen from a
  FIXED prewarmed ladder (``(1,) + cluster.K_TIERS``). The ladder is
  the contract that makes the governor free: every K it can pick is
  already a prewarmed ``STEP_CACHE`` entry, so a governed run compiles
  ZERO new programs mid-flight (``tests/test_governor.py`` pins it).
  Climb is one rung per evaluation; descent requires ``down_evals``
  consecutive evaluations of fitting a lower rung (hysteresis — a
  single shallow step never collapses a hot tier).
* **pipeline** — depth-D pipelining engages only after backlog has
  STOOD for ``engage_evals`` consecutive evaluations (the PR 6
  rationale: overlap pays only while append batches flow; in the
  latency-bound regime serial acks a commit one dispatch sooner).
* **coalesce_us** — a bounded admission wait: at high arrival rate
  with a window still filling, delaying the dispatch a few hundred µs
  fills the window and halves the dispatch count per committed entry.
  Never applied while shedding, and hard-capped — it can move latency
  by at most ``coalesce_us`` per dispatch.
* **shed** — the SLO guard: the ``commit_latency_slo_burn`` fast-burn
  pager (an ``AlertEngine.add_hook`` policy, the exact
  ``RepairController.on_alert`` pattern) drops the governor to serial
  and disengages pipelining the moment it fires, and the ladder only
  re-climbs after the alert resolves. This is what makes the governor
  a pure throughput win: it can never page the latency SLO — the
  pager IS its back-off signal.

Decisions are DETERMINISTIC given the observed step-domain inputs
(standing backlog, per-step arrival derived from backlog deltas +
accepted counts, device_committed_entries telemetry when compiled, and
the shed latch): no wall clock, no randomness — a chaos replay that
replays the same step sequence re-derives the identical tier sequence,
which is why the nemesis runners can attach a governor and keep
bit-reproducible verdicts. Tier transitions emit ``governor_tier``
trace events and ``dispatch_tier{tier=}`` counters; applied admission
waits ride the ``governor_coalesce_us`` histogram (driver-side).

:class:`HintGovernor` is the multi-host variant for ``NodeDaemon``
(``RP_GOVERNOR=1``): its decision derives ONLY from the gathered
``burst_hint`` — the PR 6 ``k_needed`` contract — so every host agrees
on the collective program schedule with no extra collective.

Host-pure module: never imports jax/numpy, never touches device state
except under the engine host lock, adds no STEP_CACHE keys
(``analysis/purity.py`` HOST_PURE_MODULES enforces it).
"""

from __future__ import annotations

import collections
import threading
from typing import Deque, List, NamedTuple, Optional, Tuple

SHED_RULE = "commit_latency_slo_burn"


class Decision(NamedTuple):
    """One published governor decision (immutable — readers on the
    dispatch thread see a complete decision or the previous one)."""
    kind: str            # "serial" | "burst" | "scan"
    max_k: int           # ladder rung; 1 == serial single step
    pipeline: bool       # engage depth-D pipelining
    coalesce_us: int     # bounded admission wait before dispatch (0=off)
    shed: bool           # SLO-shed latch active
    rungs: Tuple[int, ...]   # per-group chosen K (max_k == max(rungs))


#: the decision every governor starts from (and drains to): serial,
#: no pipelining, no coalescing — the latency-safest geometry.
SERIAL = Decision("serial", 1, False, 0, False, (1,))


def tier_label(kind: str, k: int) -> str:
    """Render a tier for the ``dispatch_tier{tier=}`` series:
    ``serial`` / ``burst4`` / ``scan16``."""
    return "serial" if k <= 1 else f"{kind}{k}"


class DispatchGovernor:
    """Step-domain feedback controller picking the dispatch tier.

    ``observe(cluster, res)`` runs at the tail of every engine
    ``finish()`` (the readback thread under pipelined drivers) and
    publishes :attr:`decision`; the drivers' dispatch paths consult it
    lock-free (a stale-by-one-step decision is by design — the same
    contract as ``cluster.last``).
    """

    def __init__(self, groups: int = 1, *,
                 batch_slots: int,
                 ladder=None,
                 down_evals: int = 4,
                 engage_evals: int = 2,
                 coalesce_us: int = 200,
                 coalesce_fill_frac: float = 0.5,
                 arrival_window: int = 8,
                 obs=None, alerts=None,
                 shed_rule: str = SHED_RULE):
        self.G = int(groups)
        self.B = int(batch_slots)
        # the fixed tier ladder: rung 0 is the serial step, the rest
        # are the engine's prewarmed fused tiers — NEVER anything
        # outside it (the zero-mid-flight-compile contract)
        self.ladder: Tuple[int, ...] = (
            (1,) + tuple(int(k) for k in ladder) if ladder
            else (1,))
        self.down_evals = int(down_evals)
        self.engage_evals = int(engage_evals)
        self.coalesce_us = int(coalesce_us)
        self.coalesce_fill_frac = float(coalesce_fill_frac)
        self.obs = obs
        # the AlertEngine whose firing set clears the shed latch; the
        # fire transition itself arrives via on_alert (add_hook)
        self.alerts = alerts
        self.shed_rule = shed_rule
        self._lock = threading.Lock()
        # per-group controller state (all step-domain):
        # current ladder rung index per group
        self._rung: List[int] = [0] * self.G   # guarded-by: _lock [writes]
        # consecutive evals the backlog fit >= one rung lower
        self._below: List[int] = [0] * self.G  # guarded-by: _lock [writes]
        # consecutive evals with standing backlog (pipeline hysteresis)
        self._standing = 0                     # guarded-by: _lock [writes]
        # previous eval's per-group backlog (arrival derivation)
        self._prev_backlog: List[int] = [0] * self.G  # guarded-by: _lock [writes]
        # trailing per-group arrival window (entries/eval)
        self._arrivals: List[Deque[int]] = [
            collections.deque(maxlen=int(arrival_window))
            for _ in range(self.G)]            # guarded-by: _lock [writes]
        # SLO-shed latch: set on the pager's fire transition, cleared
        # when the rule leaves the firing set
        self._shed = False                     # guarded-by: _lock [writes]
        self.sheds = 0
        # pinned tier (tests / operator override): decisions are fixed
        # at this tier, observation keeps running
        self._pinned: Optional[Tuple[str, int]] = None  # guarded-by: _lock [writes]
        self.evals = 0
        # the published decision — swapped whole under the lock,
        # read lock-free by the dispatch thread
        self.decision: Decision = SERIAL       # guarded-by: _lock [writes]
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    # ------------------------------------------------------------------
    # inputs
    # ------------------------------------------------------------------

    def on_alert(self, name: str, severity: str) -> None:
        """Alert→action hook (``AlertEngine.add_hook``): the fast-burn
        latency pager sheds the governor to serial immediately — tier
        drops on the FIRE transition, not the next evaluation."""
        if name != self.shed_rule:
            return
        with self._lock:
            if not self._shed:
                self._shed = True
                self.sheds += 1
                self._rung = [0] * self.G
                self._standing = 0
                self._publish_locked([0] * self.G, [0] * self.G)
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.trace.record(_trace.GOVERNOR_SHED, alert=name,
                                  severity=severity)

    def pin(self, kind: str, k: int = 1) -> None:
        """Pin every decision to one tier (``("serial", 1)`` /
        ``("burst", K)`` / ``("scan", K)``) — the bit-identity tests'
        surface and an operator escape hatch. ``k`` must sit on the
        ladder."""
        if kind not in ("serial", "burst", "scan"):
            raise ValueError(f"unknown tier kind {kind!r}")
        if kind == "serial":
            k = 1
        if int(k) not in self.ladder:
            raise ValueError(
                f"K={k} is not on the prewarmed ladder {self.ladder}")
        with self._lock:
            self._pinned = (kind, int(k))
            self._publish_locked([0] * self.G, [0] * self.G)

    def unpin(self) -> None:
        with self._lock:
            self._pinned = None

    # ------------------------------------------------------------------
    # the feedback pass (engine finish() tail, readback thread)
    # ------------------------------------------------------------------

    def observe(self, cluster, res) -> None:
        """One evaluation: derive the step-domain signals from the
        finished step and publish the next decision. Backlogs are read
        under the engine host lock (the pending queues belong to the
        dispatch/readback split)."""
        backlog = self._backlogs(cluster)
        # a deep watch backlog is demand too: the streams hub's
        # undispatched tail + subscriber queue depth drains through
        # the same committed frontier the dispatch advances (consulted
        # the way repair and elections already are; read WITHOUT the
        # engine host lock — the hub's own lock suffices and must
        # never nest inside it)
        streams = getattr(cluster, "streams", None)
        if streams is not None:
            sb = streams.backlogs()
            for g in range(min(len(sb), len(backlog))):
                backlog[g] += int(sb[g])
        accepted = self._accepted(res)
        scan = bool(getattr(cluster, "scan", False))
        # an open elastic-topology transition window holds the serial
        # tier: its seed/freeze/cutover passes ride drained serial
        # dispatches (the txn wants_serial give-way rule). The ladder
        # state keeps evaluating underneath, so the tier re-climbs on
        # the first eval after the window closes.
        topo = getattr(cluster, "topology", None)
        hold = bool(topo is not None and topo.in_window())
        with self._lock:
            self.evals += 1
            if self.alerts is not None and self._shed:
                # resolve-side of the shed latch: the pager left the
                # firing set — re-climb from serial
                if self.shed_rule not in self.alerts.firing():
                    self._shed = False
                    if self.obs is not None:
                        from rdma_paxos_tpu.obs import trace as _trace
                        self.obs.trace.record(_trace.GOVERNOR_RESUME,
                                              alert=self.shed_rule)
            arrivals = []
            for g in range(self.G):
                # entries that ARRIVED since the previous eval: the
                # backlog delta plus what this step consumed
                arr = max(0, backlog[g] - self._prev_backlog[g]
                          + accepted[g])
                self._prev_backlog[g] = backlog[g]
                self._arrivals[g].append(arr)
                arrivals.append(arr)
            if any(backlog):
                self._standing += 1
            else:
                self._standing = 0
            if not self._shed and self._pinned is None:
                for g in range(self.G):
                    # demand = standing backlog OR the trailing
                    # arrival rate, whichever is larger: at steady
                    # state a well-sized tier drains the whole take
                    # every dispatch, so post-take backlog reads ~0 —
                    # judging the rung on backlog alone would descend,
                    # spike the queue, and oscillate (a latency cost
                    # the p99 bound forbids)
                    win = self._arrivals[g]
                    rate = sum(win) // max(1, len(win))
                    self._advance_rung_locked(
                        g, max(backlog[g], rate))
            prev = self.decision
            dec = self._publish_locked(backlog, arrivals, scan=scan,
                                       hold_serial=hold)
        self._emit(prev, dec, backlog, arrivals)

    def _advance_rung_locked(self, g: int, demand: int) -> None:
        """Asymmetric ladder walk for one group over the demand
        signal (max of standing backlog and trailing arrival rate):
        climb IMMEDIATELY to the lowest rung whose capacity covers it
        (a lagging climb just queues the storm's front — the latency
        the p99 bound forbids trading away), descend one rung only
        after ``down_evals`` consecutive evaluations of fitting a
        lower tier (a single shallow eval never collapses a hot
        tier)."""
        rung = self._rung[g]
        cap = self.ladder[rung] * self.B
        if demand > cap:
            target = rung
            while (target + 1 < len(self.ladder)
                   and self.ladder[target] * self.B < demand):
                target += 1
            self._rung[g] = target
            self._below[g] = 0
            return
        lower_cap = (self.ladder[rung - 1] * self.B if rung > 0
                     else 0)
        if rung > 0 and demand <= lower_cap:
            self._below[g] += 1
            if self._below[g] >= self.down_evals:
                self._rung[g] = rung - 1
                self._below[g] = 0
        else:
            self._below[g] = 0

    # holds-lock: _lock
    def _publish_locked(self, backlog: List[int],
                        arrivals: List[int],
                        scan: bool = False,
                        hold_serial: bool = False) -> Decision:
        if self._pinned is not None:
            kind, k = self._pinned
            dec = Decision(kind, k, k > 1 and not self._shed, 0,
                           self._shed, (k,) * self.G)
            self.decision = dec
            return dec
        if self._shed:
            dec = SERIAL._replace(shed=True,
                                  rungs=(1,) * self.G)
            self.decision = dec
            return dec
        if hold_serial:
            # topology window open: serial, but NOT a shed (no latch,
            # no pager semantics) — the rung state stays put
            dec = SERIAL._replace(rungs=(1,) * self.G)
            self.decision = dec
            return dec
        rungs = tuple(self.ladder[r] for r in self._rung)
        k = max(rungs)
        kind = "serial" if k <= 1 else ("scan" if scan else "burst")
        pipeline = (k > 1 and self._standing >= self.engage_evals)
        coalesce = 0
        if k > 1 and self.coalesce_us > 0:
            total = sum(backlog)
            fill = int(self.coalesce_fill_frac * k * self.B)
            win = self._arrivals[0]
            rate = (sum(sum(a) for a in self._arrivals)
                    / max(1, len(win)))
            # admission coalescing: the stream is flowing fast enough
            # to fill the window (>= half a batch per eval) but the
            # window is not full yet — wait a bounded beat so the next
            # dispatch carries more entries
            if 0 < total < fill and rate * 2 >= self.B:
                coalesce = self.coalesce_us
        dec = Decision(kind, k, pipeline, coalesce, False, rungs)
        self.decision = dec
        return dec

    def _emit(self, prev: Decision, dec: Decision,
              backlog: List[int], arrivals: List[int]) -> None:
        if self.obs is None:
            return
        self.obs.metrics.inc("dispatch_tier",
                             tier=tier_label(dec.kind, dec.max_k))
        if (prev.max_k, prev.kind, prev.shed) != (dec.max_k, dec.kind,
                                                  dec.shed):
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.trace.record(
                _trace.GOVERNOR_TIER,
                tier=tier_label(dec.kind, dec.max_k),
                prev=tier_label(prev.kind, prev.max_k),
                pipeline=dec.pipeline, shed=dec.shed,
                backlog=int(sum(backlog)),
                arrival=int(sum(arrivals)),
                rungs=[int(k) for k in dec.rungs])

    # ------------------------------------------------------------------
    # signal extraction (engine-shape aware)
    # ------------------------------------------------------------------

    def _backlogs(self, cluster) -> List[int]:
        """Per-group standing backlog depth (max over replicas — the
        burst sizing's own rule), read under the engine host lock."""
        with cluster._host_lock:
            # the sharded engine nests pending as [G][R] even at G==1
            # (SimCluster is flat [R]) — branch on the engine shape,
            # never on the group count
            if hasattr(cluster, "G"):
                return [max(len(q) for q in cluster.pending[g])
                        for g in range(self.G)]
            return [max((len(q) for q in cluster.pending), default=0)]

    def _accepted(self, res) -> List[int]:
        """Per-group accepted-entry count for the finished step (the
        leader's append count — element max over the replica axis)."""
        acc = res.get("accepted")
        if acc is None:
            return [0] * self.G
        try:
            if getattr(acc, "ndim", 1) >= 2:      # sharded: [G, R]
                return [int(acc[g].max()) for g in range(self.G)]
            return [int(max(int(v) for v in acc))]
        except (TypeError, ValueError):
            return [0] * self.G

    def status(self) -> dict:
        with self._lock:
            d = self.decision
            return dict(tier=tier_label(d.kind, d.max_k),
                        max_k=d.max_k, pipeline=d.pipeline,
                        coalesce_us=d.coalesce_us, shed=d.shed,
                        rungs=[int(k) for k in d.rungs],
                        ladder=list(self.ladder),
                        pinned=(list(self._pinned)
                                if self._pinned else None),
                        sheds=self.sheds, evals=self.evals)


class HintGovernor:
    """The multi-host (NodeDaemon) governor: burst-vs-serial-vs-
    coalesce from the gathered ``burst_hint`` ONLY.

    Every input is a value all hosts gathered identically (full
    connectivity — the only configuration the daemon bursts in), so N
    daemons feeding the same hint sequence into N independent
    instances derive the SAME tier sequence with zero extra
    collectives — the PR 6 ``k_needed`` contract extended to the
    governor (``tests/test_governor.py`` pins the agreement).

    The daemon compiles exactly ONE burst program (every distinct K is
    a separate multi-process compile), so there is no ladder here; the
    governable axis is admission coalescing: when the gathered backlog
    is small but RISING, hold the batch for up to ``coalesce_limit``
    iterations (a serial heartbeat step that takes no batch) so the
    next burst rides a fuller window.
    """

    def __init__(self, batch_slots: int, *, coalesce_limit: int = 2,
                 window: int = 8):
        self.B = int(batch_slots)
        self.coalesce_limit = int(coalesce_limit)
        self._hints: Deque[int] = collections.deque(maxlen=int(window))
        self._coalesced = 0
        self.decisions = collections.Counter()

    def decide(self, hint: int) -> str:
        """-> ``"step"`` | ``"burst"`` | ``"coalesce"`` for the next
        iteration, from the gathered hint only (deterministic, pure —
        the host-agreement contract)."""
        hint = int(hint)
        prev = self._hints[-1] if self._hints else 0
        self._hints.append(hint)
        if hint <= 0:
            self._coalesced = 0
            out = "step"
        elif hint >= self.B:
            self._coalesced = 0
            out = "burst"
        elif hint > prev and self._coalesced < self.coalesce_limit:
            # small but rising: hold admission one beat — bounded, so
            # a stalling stream never waits more than coalesce_limit
            # iterations before the partial window ships
            self._coalesced += 1
            out = "coalesce"
        else:
            self._coalesced = 0
            out = "burst"
        self.decisions[out] += 1
        return out


def attach_governor(cluster, *, obs=None, alerts=None,
                    **opts) -> DispatchGovernor:
    """Enable the governor on an engine (SimCluster or ShardedCluster,
    any execution mode): hangs a :class:`DispatchGovernor` on
    ``cluster.governor`` — the engines' ``finish()`` observes it from
    then on (the ``leases``/``reads`` attach pattern). The ladder is
    derived from the engine's OWN prewarmed tier set, so a governed
    run can never compile a program the ungoverned engine would not.
    Pure host bookkeeping: programs and STEP_CACHE keys untouched."""
    gov = DispatchGovernor(
        groups=int(getattr(cluster, "G", 1)),
        batch_slots=cluster.cfg.batch_slots,
        ladder=cluster.K_TIERS,
        obs=(obs if obs is not None else cluster.obs),
        alerts=alerts, **opts)
    cluster.governor = gov
    return gov
