"""Self-healing repair pipeline — DIVERGENCE → quarantine →
digest-verified snapshot re-install → range-digest backfill →
re-admit.

PR 5 made silent divergence a detected, localized failure
(``obs/audit.py`` names the exact first ``(term, index)`` and the
minority replica set) and PR 8 gave it a device-truth trigger surface
— but detection alone leaves a corrupted replica voting, serving, and
donating snapshots. APUS's value proposition is that replica failure
is survived and repaired WITHOUT operator action (leader election +
snapshot recovery + live membership, SURVEY/PAPER §0), and the
recovery path itself must be fast and verified (DXRAM, arXiv:
1807.03562; RDMA-agreement recovery correctness, arXiv:1905.12143).
This module closes that loop:

1. **Quarantine** — a new DIVERGENCE finding names a minority replica:
   it is cut from the hear-matrix (no votes, no window absorption —
   the peer-mask machinery partitions/crashes already use), folded
   into the engines' ``need_recovery`` set (replay to the app stops;
   the rebase min excludes it), excluded from client serving and
   leader placement by the drivers, and exported as
   ``replica_quarantined{replica=,group=}`` + a trace event.
2. **Digest-verified snapshot re-install** — the donor comes from the
   ledger's MAJORITY set (never the diverged minority);
   ``take_snapshot(digests=True)`` folds the donor's audit-chain
   position (absolute indices + layout epoch) into the snapshot and
   ``install_snapshot(ledger=...)`` REFUSES a donor whose digests
   contradict the ledger's majority — a corrupted donor is rejected
   at install time, never propagated; the controller retries with the
   next majority donor.
3. **Range-digest backfill** — the jitted ``[lo, hi)`` re-digest pass
   (``consensus/step.py:build_redigest`` — the exact ``audit=`` fold,
   cache-key guarded under a distinct ``"redigest"`` marker) restores
   gap-free ledger coverage over the repaired range, so the cluster
   returns to *fully-audited* health, not just healed state;
   ``AuditLedger.mark_repaired`` closes the findings.
4. **Re-admit with hysteresis** — the replica rejoins consensus
   immediately (it must absorb windows to catch up) but serves
   clients again only after ``probation_steps`` clean audited steps;
   a repeat divergence during probation re-quarantines.
5. **Bounded retry/backoff** — a repair attempt that exhausts every
   donor backs off (linearly growing, in STEP-domain time so chaos
   replays are bit-reproducible) and after ``max_attempts`` escalates
   to a LATCHED page (``repair_escalated_total`` →
   ``repair_failed`` in ``obs/alerts.py:default_rules``) instead of
   looping forever.

Threading contract (the PR 6 pipelined driver): :meth:`observe` runs
after every finished step — host bookkeeping only, safe on the
readback thread. :meth:`drive` performs the state surgery and runs
ONLY on a drained serial iteration (the drivers' ``_pipeline_ready``
returns False while :meth:`needs_drain`, the same
``require_drained``/deferral contract ``_drive_config_change`` uses);
per-group quarantine never stalls healthy groups — their dispatches
resume the moment the one drained repair iteration returns.

Engine-agnostic: works on ``SimCluster`` (single group) and
``ShardedCluster`` (per-group, vmap or mesh engine) through the shared
snapshot/redigest primitives; drivers can override the install with a
hook that also transfers stores/app state
(``ClusterDriver._do_recover``).
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from rdma_paxos_tpu.consensus.snapshot import (
    SnapshotVerifyError, install_snapshot, recover_vote, take_snapshot)
from rdma_paxos_tpu.runtime.hostpath import stream_copy as _stream_copy

QUARANTINED = "quarantined"
PROBATION = "probation"
ESCALATED = "escalated"


class RepairController:
    """The quarantine→repair→backfill→re-admit state machine, driven
    from the cluster drivers' poll loops (or a chaos runner)."""

    def __init__(self, cluster, *, obs=None, probation_steps: int = 6,
                 max_attempts: int = 3, backoff_steps: int = 8,
                 min_verified: int = 1, install_hook=None,
                 post_install=None, storm_policy: bool = False,
                 storm_min: int = 3):
        if cluster.auditor is None:
            raise ValueError("repair requires an audit=True cluster "
                             "(the ledger is the donor-selection and "
                             "verification authority)")
        if getattr(cluster, "_fanout", "gather") == "psum":
            # quarantine isolation IS a peer-mask cut, and the psum
            # fan-out rejects any non-full mask at dispatch — the
            # first quarantine would kill the serving loop mid-heal.
            # Fail at construction, the way partitions/chaos do.
            raise ValueError(
                "repair requires fanout='gather' (quarantine cuts the "
                "hear-matrix; psum fan-out rejects non-full masks)")
        self.cluster = cluster
        self.led = cluster.auditor
        self.obs = obs
        self._sharded = np.asarray(cluster.applied).ndim == 2
        self.G = int(getattr(cluster, "G", 1))
        self.R = int(cluster.R)
        self.probation_steps = int(probation_steps)
        self.max_attempts = int(max_attempts)
        self.backoff_steps = int(backoff_steps)
        self.min_verified = int(min_verified)
        # driver hooks: install_hook(g, r, donor) REPLACES the
        # engine-level install (e.g. ClusterDriver._do_recover — store
        # transfer + app replay included; must raise
        # SnapshotVerifyError on a bad donor so donor retry works);
        # post_install(g, r, donor) runs AFTER the engine-level
        # install (e.g. the sharded driver's replay-cursor fixup);
        # on_quarantine(g, r) fires on each NEW quarantine, invoked
        # OUTSIDE the controller lock (the sharded driver fails the
        # held front-end's commit waiters there — a hook that takes
        # the driver lock must never nest inside ours, the reverse
        # edge already exists via the serving gates).
        self.install_hook = install_hook
        self.post_install = post_install
        self.on_quarantine = None
        self._lock = threading.RLock()
        # (g, r) -> dict(state=, attempts=, next_try=, clean=,
        #                finding=, last_step=)
        # guarded-by: _lock
        self.states: Dict[Tuple[int, int], dict] = {}
        # deterministic evidence: step-domain events only (no wall
        # clock) so same-seed chaos verdicts embed identical timelines.
        # Bounded like every other evidence surface (trace ring /
        # flight recorder): a long-lived flapping replica must not
        # grow an unbounded list that every health() poll then copies.
        self.timeline: collections.deque = collections.deque(
            maxlen=256)
        self.timeline_dropped = 0
        self._seen_findings = 0
        self.repairs_done = 0
        self.donors_rejected = 0
        self.escalations = 0
        # telemetry-triggered quarantine policy (opt-in): a firing
        # election_storm page (device-truth elections_started rate,
        # obs/device.py series) quarantines the storming replica
        # WITHOUT a digest finding — link cut + serving/lease/read
        # refusal + probation, but no snapshot re-install (its state
        # never diverged; the storm is a liveness hazard, not a
        # correctness one)
        self.storm_policy = bool(storm_policy)
        self.storm_min = int(storm_min)
        self._storm_prev: Dict[str, float] = {}
        self._storm_tick = 0
        self.policy_quarantines = 0

    # ------------------------------------------------------------------
    # helpers over the two engine shapes
    # ------------------------------------------------------------------

    def _key_of_recovery(self, g: int, r: int):
        return (g, r) if self._sharded else r

    def _rebased(self, g: int) -> int:
        rt = self.cluster.rebased_total
        return int(rt[g]) if self._sharded else int(rt)

    def _applied(self, g: int, r: int) -> int:
        a = self.cluster.applied
        return int(a[g, r]) if self._sharded else int(a[r])

    def _step_index(self) -> int:
        return int(self.cluster.step_index)

    def _cut_mask(self, g: int, r: int) -> None:
        pm = self.cluster.peer_mask
        if self._sharded:
            pm[g, r, :] = 0
            pm[g, :, r] = 0
            pm[g, r, r] = 1
        else:
            pm[r, :] = 0
            pm[:, r] = 0
            pm[r, r] = 1

    # holds-lock: _lock
    def _restore_mask(self, g: int, r: int) -> None:
        # restore hearing to every peer EXCEPT ones this controller
        # still holds — re-opening a link to a second, still-diverged
        # quarantined replica would break ITS isolation invariant.
        # Quarantine composes with the chaos link models (they refine
        # the base mask per step), but not with a concurrently
        # scripted base partition of the same replica; drivers never
        # do both.
        pm = self.cluster.peer_mask
        still_cut = {rr for (gg, rr), st in self.states.items()
                     if gg == g and rr != r
                     and st["state"] in (QUARANTINED, ESCALATED)}
        for p in range(self.R):
            if p in still_cut:
                continue
            if self._sharded:
                pm[g, r, p] = 1
                pm[g, p, r] = 1
            else:
                pm[r, p] = 1
                pm[p, r] = 1

    def _block_reads(self, g: int, r: int) -> None:
        """Bar ``(g, r)`` from read serving for the WHOLE hold
        (quarantine through probation): ``need_recovery`` alone does
        not cover policy holds (their replay keeps running) and is
        discarded at install time, before probation ends."""
        rb = getattr(self.cluster, "read_blocked", None)
        if rb is not None:
            rb.add(self._key_of_recovery(g, r))

    def _unblock_reads(self, g: int, r: int) -> None:
        rb = getattr(self.cluster, "read_blocked", None)
        if rb is not None:
            rb.discard(self._key_of_recovery(g, r))

    def _revoke_lease(self, g: int, r: int) -> None:
        """A held replica must not serve lease reads: revoke BEFORE
        serving gates react (runtime/reads.py — revocation arms the
        wait-out barrier so no successor lease activates early)."""
        lm = getattr(self.cluster, "leases", None)
        if lm is not None:
            lm.revoke(g, r, reason="quarantine")

    def _gauge(self, g: int, r: int, v: int) -> None:
        if self.obs is not None:
            self.obs.metrics.set("replica_quarantined", v,
                                 replica=r, group=g)

    def _trace(self, event: str, **fields) -> None:
        if self.obs is not None:
            self.obs.trace.record(event, **fields)

    def _mark(self, event: str, g: int, r: int, **extra) -> None:
        rec = dict(event=event, step=self._step_index(), group=g,
                   replica=r, **extra)
        if len(self.timeline) == self.timeline.maxlen:
            self.timeline_dropped += 1      # ring full: oldest evicted
        self.timeline.append(rec)
        self._trace(event, **{k: v for k, v in rec.items()
                              if k != "event"})

    # ------------------------------------------------------------------
    # observation (every finished step; readback-thread safe)
    # ------------------------------------------------------------------

    def observe(self) -> None:
        """Consume new ledger findings (quarantine newly implicated
        minority replicas) and advance probation hysteresis — host
        bookkeeping only; never touches device state."""
        # keep the storm-attribution baseline FRESH on a stride: the
        # deltas _storm_replicas reads must reflect recent elections,
        # not lifetime totals — an un-refreshed baseline would blame
        # whichever replica churned most EVER (e.g. early-run leader
        # churn) instead of the replica storming NOW. The stride keeps
        # the per-step registry snapshot off most observe passes.
        if self.storm_policy and self.obs is not None:
            self._storm_tick += 1
            if self._storm_tick % 8 == 0:
                with self._lock:
                    self._storm_refresh()
        newly_q: List[Tuple[int, int]] = []
        with self._lock:
            findings = self.led.findings
            fresh = findings[self._seen_findings:]
            self._seen_findings = len(findings)
            implicated: Set[Tuple[int, int]] = set()
            for f in fresh:
                if f.get("type", "DIVERGENCE") != "DIVERGENCE":
                    continue        # epoch refusals are config errors
                for r in f["got_replicas"]:
                    key = (int(f.get("group", 0)), int(r))
                    implicated.add(key)
                    if self._quarantine(key[0], key[1], f):
                        newly_q.append(key)
            # probation: N clean audited steps before serving again —
            # AND a closed audit trail (a backfill whose coverage was
            # still accruing re-checks here until it closes)
            step = self._step_index()
            for key, st in list(self.states.items()):
                if st["state"] != PROBATION:
                    continue
                if key in implicated:
                    continue        # _quarantine already re-flagged it
                if st.get("pending") is not None and \
                        self._try_close(key[0], key[1], st["pending"]):
                    st["pending"] = None
                # one clean unit per OBSERVED audit pass, not per
                # step-index delta: a K=8 fused burst is one audited
                # observation, and must not satisfy the whole
                # hysteresis in a single post-repair window
                if step > st["last_step"]:
                    st["clean"] += 1
                    st["last_step"] = step
                if st["clean"] >= self.probation_steps \
                        and st.get("pending") is None:
                    self._readmit(key)
        # hooks fire OUTSIDE the controller lock (see __init__)
        if self.on_quarantine is not None:
            for (g, r) in newly_q:
                try:
                    self.on_quarantine(g, r)
                except Exception:  # noqa: BLE001 — a failing hook
                    pass           # must never kill the observe pass

    # holds-lock: _lock
    def _quarantine(self, g: int, r: int, finding: dict) -> bool:
        """Returns True when ``(g, r)`` newly entered (or re-entered)
        quarantine this call."""
        key = (g, r)
        st = self.states.get(key)
        if st is not None and st["state"] == QUARANTINED:
            return False            # already isolated
        if st is not None and st["state"] == ESCALATED:
            return False            # latched — operator territory
        c = self.cluster
        with c._host_lock:
            c.need_recovery.add(self._key_of_recovery(g, r))
            self._block_reads(g, r)
            self._cut_mask(g, r)
        self._revoke_lease(g, r)
        attempts = st["attempts"] if st is not None else 0
        self.states[key] = dict(
            state=QUARANTINED, attempts=attempts,
            next_try=self._step_index(), clean=0, finding=dict(finding),
            last_step=self._step_index())
        self._gauge(g, r, 1)
        if self.obs is not None:
            self.obs.metrics.inc("replicas_quarantined_total",
                                 replica=r, group=g)
        self._mark("replica_quarantined", g, r,
                   index=finding.get("index"),
                   term=finding.get("term"),
                   requarantine=st is not None)
        return True

    # ------------------------------------------------------------------
    # repair drive (drained serial iterations only)
    # ------------------------------------------------------------------

    def needs_drain(self) -> bool:
        """True iff a repair action is due — the drivers' pipeline
        gates read this (same deferral contract as config changes)."""
        with self._lock:
            step = self._step_index()
            return any(st["state"] == QUARANTINED
                       and st["next_try"] <= step
                       for st in self.states.values())

    def drive(self) -> List[Tuple[int, int]]:
        """Attempt due repairs. Runs the state surgery, so callers
        must be on the drained serial path; with dispatches still in
        flight the call DEFERS (returns []) exactly like
        ``_drive_config_change``. Returns the (group, replica) keys
        repaired this call (chaos runners reset their invariant
        baselines for them)."""
        c = self.cluster
        topo = getattr(c, "topology", None)
        if topo is not None and topo.frozen():
            # a topology cutover is mid-freeze: repair's config
            # surgery must not interleave with the router swap — give
            # way for the (step-bounded) freeze. Symmetric rule: the
            # topology window abandons its freeze the moment repair
            # quarantines a replica in an affected group, so neither
            # side can wait the other out.
            return []
        with c._host_lock:
            if c._tickets:
                return []           # defer until the pipeline drains
        repaired: List[Tuple[int, int]] = []
        with self._lock:
            step = self._step_index()
            due = sorted(k for k, st in self.states.items()
                         if st["state"] == QUARANTINED
                         and st["next_try"] <= step)
            for key in due:
                if self._repair_one(key):
                    repaired.append(key)
        return repaired

    # holds-lock: _lock
    def _donor_candidates(self, g: int, r: int) -> List[int]:
        """Majority-set donor order: never the diverged minority (the
        ledger's implicated set), never another quarantined replica;
        most caught-up first (Raft's election ordering picks donors
        the same way)."""
        bad = {rr for rr in range(self.R)
               if (g, rr) in self.states}
        bad |= self.led.implicated_replicas(g)
        cands = [p for p in range(self.R) if p != r and p not in bad]
        return sorted(cands, key=lambda p: (-self._applied(g, p), p))

    # holds-lock: _lock
    def _repair_one(self, key: Tuple[int, int]) -> bool:
        g, r = key
        st = self.states[key]
        if st.get("policy"):
            # policy quarantine (no digest finding): the replica's
            # state never diverged, so there is nothing to re-install
            # or backfill — restore its links and let the clean-step
            # probation hysteresis gate re-admission (a repeat storm
            # during probation re-quarantines via on_alert)
            with self.cluster._host_lock:
                self._restore_mask(g, r)
            st.update(state=PROBATION, clean=0, pending=None,
                      last_step=self._step_index())
            self._mark("repair_policy_released", g, r,
                       reason=st["finding"].get("reason"))
            return True
        for donor in self._donor_candidates(g, r):
            try:
                snap_info = self._install_from(g, r, donor)
            except RuntimeError as exc:
                # SnapshotVerifyError = donor corrupted/unverifiable;
                # other RuntimeErrors (e.g. a driver install_hook's
                # store mismatch) also mean "this donor won't do" —
                # either way, try the next majority donor, never die
                self.donors_rejected += 1
                if self.obs is not None:
                    self.obs.metrics.inc("repair_donor_rejected_total",
                                         group=g)
                self._mark("repair_donor_rejected", g, r, donor=donor,
                           verify=isinstance(exc, SnapshotVerifyError),
                           error=str(exc)[:160])
                continue
            # success: backfill coverage, close findings, probation.
            # If the coverage verdict is not yet gap-free+majority
            # (the newest indices lag one lazy-push step behind the
            # followers' re-reports), the range stays PENDING and the
            # probation pass re-checks it every step — re-admission
            # requires BOTH the clean-step hysteresis AND the closed
            # audit trail.
            pending = self._backfill(g, r, donor, snap_info)
            st.update(state=PROBATION, clean=0, pending=pending,
                      last_step=self._step_index())
            self.repairs_done += 1
            if self.obs is not None:
                self.obs.metrics.inc("repairs_total", group=g)
            return True
        # no donor worked: back off; escalate past the retry budget
        st["attempts"] += 1
        if st["attempts"] >= self.max_attempts:
            st["state"] = ESCALATED
            self.escalations += 1
            if self.obs is not None:
                # the LATCHED page signal: counter_nonzero never
                # un-fires (obs/alerts.py default rule repair_failed)
                self.obs.metrics.inc("repair_escalated_total", group=g)
            self._mark("repair_escalated", g, r,
                       attempts=st["attempts"])
        else:
            st["next_try"] = (self._step_index()
                              + self.backoff_steps * st["attempts"])
            self._mark("repair_backoff", g, r, attempts=st["attempts"],
                       next_try=st["next_try"])
        return False

    def _install_from(self, g: int, r: int, donor: int) -> dict:
        """One digest-verified snapshot transfer donor→r; raises
        SnapshotVerifyError (propagated to donor retry) on a
        corrupted/unverifiable donor, BEFORE any state changes."""
        c = self.cluster
        reb = self._rebased(g)
        if self.install_hook is not None:
            self.install_hook(g, r, donor)
            snap_index = self._applied(g, r)
            audit_lo_raw = None       # hook path: derive from head
        else:
            grp = g if self._sharded else None
            snap = take_snapshot(
                c.state, donor, index=self._applied(g, donor),
                group=grp, digests=True, rebased_total=reb)
            vt, vf = recover_vote(c.state, r, group=grp)
            with c._host_lock:
                c.state = install_snapshot(
                    c.state, r, snap, voted_term=vt, voted_for=vf,
                    group=grp, ledger=self.led, ledger_group=g,
                    min_verified=self.min_verified)
                if self._sharded:
                    c.applied[g, r] = snap.index
                    c.replayed[g][r] = _stream_copy(
                        c.replayed[g][donor])
                    c.frames[g][r] = []
                else:
                    c.applied[r] = snap.index
                    c.replayed[r] = _stream_copy(c.replayed[donor])
                    c.frames[r] = []
            snap_index = snap.index
            # the verified chain may have been truncated from below
            # (slot recycled mid-capture): the backfill must cover
            # exactly the range the snapshot PROVED, not re-derive it
            # from a head that has moved since
            audit_lo_raw = (snap.audit_start - reb
                            if snap.audit_start >= 0 else None)
            if self.post_install is not None:
                self.post_install(g, r, donor)
        with c._host_lock:
            c.need_recovery.discard(self._key_of_recovery(g, r))
            self._restore_mask(g, r)
        # the re-installed replica's next reports legitimately differ
        # from its pre-repair memory — the self-recheck must not flag
        self.led.reset_replica(g, r)
        self._mark("repair_installed", g, r, donor=donor,
                   index=snap_index + reb)
        return dict(donor=donor, index=snap_index, rebased=reb,
                    audit_lo=audit_lo_raw)

    def _backfill(self, g: int, r: int, donor: int,
                  info: dict) -> Optional[dict]:
        """Range re-digest over the donor's physically-present
        committed range. The findings close (``mark_repaired``) ONLY
        once :meth:`AuditLedger.coverage` verdicts the range gap-free
        and majority-held — an immediate pass when the live windows
        already co-signed the whole range, else the range is returned
        as PENDING and the probation pass re-checks it every step
        (the newest indices lag the followers' re-reports by one
        lazy-push step; a genuinely un-coverable range keeps the
        findings open, the page latched, and re-admission blocked —
        the audit trail never claims closure it cannot prove)."""
        c = self.cluster
        reb = info["rebased"]
        hi_raw = info["index"]
        lo_raw = info.get("audit_lo")
        if lo_raw is None:
            # driver install_hook path (no snapshot in hand): the
            # donor's ring floor bounds the re-digestable range
            if self._sharded:
                head = int(np.asarray(c.state.head[g, donor]))
            else:
                head = int(np.asarray(c.state.head[donor]))
            lo_raw = max(head, 0)
        n = 0
        try:
            if hi_raw > lo_raw:
                if self._sharded:
                    n = c.redigest(g, donor, lo_raw, hi_raw)
                else:
                    n = c.redigest(donor, lo_raw, hi_raw)
        except RuntimeError as exc:
            # a slot recycled under the re-digest (or a transient
            # integrity failure) must degrade to an OPEN audit trail
            # — never crash the serving poll loop the drive() caller
            # sits on. The range stays pending-with-zero-coverage:
            # findings stay open, the divergence page stays latched,
            # the replica stays in probation for the operator.
            self._mark("repair_backfill_error", g, r, donor=donor,
                       lo=lo_raw + reb, hi=hi_raw + reb,
                       error=str(exc)[:160])
            return dict(lo=lo_raw + reb, hi=hi_raw + reb, donor=donor,
                        indices=0)
        lo_abs, hi_abs = lo_raw + reb, hi_raw + reb
        pend = dict(lo=lo_abs, hi=hi_abs, donor=donor, indices=n)
        if self._try_close(g, r, pend):
            return None
        self._mark("repair_backfill_pending", g, r, donor=donor,
                   lo=lo_abs, hi=hi_abs, indices=n)
        return pend

    def _try_close(self, g: int, r: int, pend: dict) -> bool:
        """Attempt audit-trail closure for a backfilled range: when
        coverage is gap-free + majority-held, ``mark_repaired`` closes
        the findings and the closure is recorded. False = still
        pending (re-checked from the probation pass)."""
        cov = self.led.coverage(g, pend["lo"], pend["hi"])
        if pend["indices"] == 0 or not cov["ok"]:
            return False
        rec = self.led.mark_repaired(
            g, r, pend["lo"], pend["hi"], donor=pend["donor"],
            index=pend["hi"], step=self._step_index())
        if self.obs is not None:
            self.obs.metrics.inc("repair_backfilled_indices_total",
                                 pend["indices"], group=g)
        self._mark("repair_backfilled", g, r, donor=pend["donor"],
                   lo=rec["lo"], hi=rec["hi"],
                   indices=pend["indices"])
        return True

    # holds-lock: _lock
    def _readmit(self, key: Tuple[int, int]) -> None:
        g, r = key
        del self.states[key]
        self._unblock_reads(g, r)
        self._gauge(g, r, 0)
        if self.obs is not None:
            self.obs.metrics.inc("repair_readmitted_total", group=g)
        self._mark("repair_readmitted", g, r,
                   probation=self.probation_steps)

    # ------------------------------------------------------------------
    # driver queries
    # ------------------------------------------------------------------

    def serving_blocked(self, g: int, r: int) -> bool:
        """True while ``(g, r)`` must not serve clients or hold
        leadership (quarantined, on probation, or escalated)."""
        with self._lock:
            return (g, r) in self.states

    def serving_blocked_any(self, r: int) -> bool:
        """True while replica ``r`` is held in ANY group — the sharded
        front-end admission gate (a held replica's replay for the held
        group is frozen, so sessions it admits could stall on acks)."""
        with self._lock:
            return any(rr == r for (_g, rr) in self.states)

    def owned(self) -> Set:
        """``need_recovery`` members this controller manages — the
        drivers' default auto-recovery must leave them alone (keys in
        the engine's own need_recovery shape)."""
        with self._lock:
            return {self._key_of_recovery(g, r)
                    for (g, r) in self.states}

    def blocked_replicas_locked(self, group: int) -> Set[int]:
        """Caller holds ``self._lock``."""
        return {r for (g, r) in self.states if g == group}

    def blocked_replicas(self, group: int = 0) -> Set[int]:
        with self._lock:
            return self.blocked_replicas_locked(group)

    def on_alert(self, name: str, severity: str) -> None:
        """Alert→action hook (``AlertEngine.add_hook``): a firing
        digest-divergence page triggers an immediate findings scan so
        quarantine never waits for the next step's observe pass; a
        firing election-storm page (with ``storm_policy=True``)
        quarantines the storming replica without a digest finding."""
        if name == "digest_divergence":
            self.observe()
        elif name == "election_storm" and self.storm_policy:
            self._storm_quarantine()

    def _storm_refresh(self) -> Dict[Tuple[int, int], float]:
        """Advance the per-series storm baseline and return the
        per-(group, replica) deltas since the previous refresh — read
        from the registry's
        ``device_elections_started_total{replica=,group=}`` series
        (the PR 8 device-truth surface the election_storm rule fires
        on)."""
        from rdma_paxos_tpu.obs.alerts import _split_key
        deltas: Dict[Tuple[int, int], float] = {}
        snap = self.obs.metrics.snapshot()["counters"]
        for key, total in snap.items():
            base, labels = _split_key(key)
            if base != "device_elections_started_total":
                continue
            delta = total - self._storm_prev.get(key, 0)
            self._storm_prev[key] = total
            if delta <= 0:
                continue
            gr = (int(labels.get("group", 0)),
                  int(labels.get("replica", -1)))
            if gr[1] >= 0:
                deltas[gr] = deltas.get(gr, 0) + delta
        return deltas

    def _storm_replicas(self) -> List[Tuple[int, int]]:
        """The replicas whose DEVICE election counter advanced most
        since the last baseline refresh (recent activity, not
        lifetime totals — observe() keeps the baseline fresh)."""
        if self.obs is None:
            return []
        deltas = self._storm_refresh()
        worst = max(deltas.values(), default=0)
        if worst < self.storm_min:
            return []
        return sorted(gr for gr, d in deltas.items() if d == worst)

    def _storm_quarantine(self) -> List[Tuple[int, int]]:
        held = []
        with self._lock:
            for (g, r) in self._storm_replicas():
                # never cut the group below a connected majority: the
                # digest path holds one implicated minority finding at
                # a time, and the policy path gets the same budget —
                # two rivals storming in lock-step must not combine
                # into a self-inflicted total outage
                already = len(self.blocked_replicas_locked(g))
                if already + 1 > (self.R - 1) // 2:
                    self._mark("storm_hold_refused", g, r,
                               held=already)
                    continue
                if self._policy_quarantine(g, r, "election_storm"):
                    held.append((g, r))
        if self.on_quarantine is not None:
            for (g, r) in held:        # hooks outside our lock
                try:
                    self.on_quarantine(g, r)
                except Exception:  # noqa: BLE001 — hooks never kill
                    pass           # the alert-evaluating poll loop
        return held

    # holds-lock: _lock
    def _policy_quarantine(self, g: int, r: int,
                           reason: str) -> bool:
        """Quarantine WITHOUT a digest finding (caller holds our
        lock): link cut + serving/lease refusal, but the replica's
        replay keeps running (its state is not suspect) and drive()
        releases it straight to probation — no install, no
        backfill."""
        if (g, r) in self.states:
            return False            # already held / escalated
        with self.cluster._host_lock:
            self._cut_mask(g, r)
            self._block_reads(g, r)
        self._revoke_lease(g, r)
        step = self._step_index()
        self.states[(g, r)] = dict(
            state=QUARANTINED, attempts=0, next_try=step, clean=0,
            finding=dict(type="POLICY", reason=reason),
            last_step=step, policy=True)
        self.policy_quarantines += 1
        self._gauge(g, r, 1)
        if self.obs is not None:
            self.obs.metrics.inc("replicas_policy_quarantined_total",
                                 replica=r, group=g)
        self._mark("replica_quarantined", g, r, policy=True,
                   reason=reason)
        return True

    def status(self) -> dict:
        """Deterministic (step-domain, no wall clock) state export for
        health snapshots, chaos verdicts, and reproducer artifacts."""
        with self._lock:
            return dict(
                active={f"{g}:{r}": dict(st, finding=None)
                        for (g, r), st in self.states.items()},
                repairs_done=self.repairs_done,
                donors_rejected=self.donors_rejected,
                escalations=self.escalations,
                policy_quarantines=self.policy_quarantines,
                probation_steps=self.probation_steps,
                max_attempts=self.max_attempts,
                timeline=[dict(t) for t in self.timeline],
                timeline_dropped=self.timeline_dropped,
            )
