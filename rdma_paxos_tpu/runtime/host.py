"""Multi-host deployment — one consensus replica per host (per chip).

This is the TRUE distributed topology matching the reference's one-process-
per-machine deployment over InfiniBand (``benchmarks/run.sh`` starting N
replicas over ssh). The mapping of the reference's transports:

  IB multicast bootstrap (mcast JOIN,     jax.distributed.initialize —
  ud_exchange_rc_info 3-way handshake)    coordinator rendezvous + PJRT
                                          device exchange over DCN
  RC QP data plane (one-sided writes)     XLA collectives over ICI/DCN
                                          inside the jitted SPMD step
  per-peer MR/rkey exchange               handled by the runtime (no app-
                                          level analog needed)

Every host runs the SAME SPMD programs in the same order (multi-controller
JAX); per-host *values* differ — each host feeds its replica's StepInput
shard (client batches from its local proxy, its own election timer) and
reads back its replica's output shard. The collectives inside the step
synchronize the hosts, so the polling loops stay in lock-step naturally.

Usage (per host)::

    hd = HostReplicaDriver(cfg, process_id=i, num_processes=N,
                           coordinator="host0:9900")
    hd.step(batch=[...], timeout_fired=..., apply_done=...)  # every host
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, META_W
from rdma_paxos_tpu.consensus.step import StepInput, fetch_window
from rdma_paxos_tpu.parallel.mesh import (
    REPLICA_AXIS, build_spmd_step, stack_states)

# per-replica scalar outputs extracted from a step/burst (ONE list so the
# single-step and burst paths can never drift)
OUT_KEYS = ("term", "role", "leader_id", "voted_term", "voted_for",
            "head", "apply", "commit", "end", "hb_seen", "became_leader",
            "acked", "accepted", "leadership_verified", "burst_hint",
            "rebase_delta")


class HostReplicaDriver:
    """Per-host runtime for one replica of a multi-host group."""

    def __init__(self, cfg: LogConfig, *, process_id: int,
                 num_processes: int, coordinator: str,
                 group_size: Optional[int] = None,
                 initialize_distributed: bool = True,
                 fanout: str = "psum", audit: bool = False):
        if initialize_distributed:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes, process_id=process_id)
        self.cfg = cfg
        self.me = process_id
        self.R = num_processes
        devs = jax.devices()
        if len(devs) < self.R:
            raise RuntimeError(
                f"need {self.R} global devices, have {len(devs)}")
        self.mesh = Mesh(np.array(devs[:self.R]), (REPLICA_AXIS,))
        self._sharding = NamedSharding(self.mesh, P(REPLICA_AXIS))
        # real deployments run full-connectivity meshes: the O(W) psum
        # fan-out is sound there (see replica_step's fanout docstring)
        self._fanout = fanout
        # audit=True compiles the digest-chain variant (see
        # consensus/step.py): each host extracts ITS replica's digest
        # windows and records them locally; cross-host comparison
        # happens by merging the per-replica audit dumps
        # (python -m rdma_paxos_tpu.obs.audit report ...)
        self._audit = audit
        self._step = build_spmd_step(
            cfg, self.R, self.mesh, fanout=fanout, audit=audit,
            # same kernel as the benches: Pallas quorum scan on TPU
            use_pallas=jax.default_backend() == "tpu")
        # one jitted burst builder (lazily built): the scan length
        # follows the [K, ...] input shape, so jit specializes per K
        self._burst = None
        # the K-window scan tier (lazily built; RP_SCAN=1 daemons):
        # fused steps + consolidated readback + local replay window
        self._scan = None
        self._ksharding = NamedSharding(self.mesh, P(None, REPLICA_AXIS))

        # HOST-LOCAL window fetch: reads THIS replica's log shard only —
        # a single-device program outside the SPMD step, so hosts may
        # call it independently (or not at all on idle iterations). The
        # collective window fetch this replaces forced every host into a
        # second lock-step program per iteration.
        from rdma_paxos_tpu.consensus.log import Log as _Log
        self._local_fetch = jax.jit(
            lambda buf, start: fetch_window(
                _Log(buf=buf), start, window_slots=cfg.window_slots))

        self.state = jax.device_put(stack_states(cfg, self.R, group_size
                                                 or self.R),
                                    self._sharding)
        self._local_dev = self.mesh.devices.flat[self.me]
        # persistent zero-copy staging buffers for window encode:
        # allocated once, repacked in place each iteration with only
        # the previously-dirty rows zeroed (per-step [B,...] allocation
        # + full memset was a measurable share of host_encode). Safe to
        # reuse because step()/step_burst() extract their outputs
        # before returning — the lock-step daemon never has a dispatch
        # in flight when the next iteration repacks.
        B = cfg.batch_slots
        self._stage = dict(
            data=np.zeros((B, cfg.slot_words), np.int32),
            meta=np.zeros((B, META_W), np.int32), dirty=0)
        self._kstage: Dict[int, dict] = {}   # K -> burst staging set

    # ------------------------------------------------------------------

    def install_genesis(self, row: dict) -> None:
        """Install an identical pre-synchronized state row on EVERY
        replica of the world — the elastic-rebuild boot path (see
        ``consensus/snapshot.genesis_row``). Collective: every host calls
        this at the same point with the SAME row (all fetched it from the
        generation's donor)."""
        import dataclasses as _dc
        from rdma_paxos_tpu.consensus.log import Log
        from rdma_paxos_tpu.consensus.state import ReplicaState

        def put(leaf: np.ndarray) -> jax.Array:
            shards = [jax.device_put(leaf[None], d)
                      for d in self.mesh.devices.flat
                      if d.process_index == jax.process_index()]
            return jax.make_array_from_single_device_arrays(
                (self.R,) + leaf.shape, self._sharding, shards)

        fields = {}
        for f in _dc.fields(ReplicaState):
            if f.name == "log":
                continue
            cur = getattr(self.state, f.name)
            fields[f.name] = put(np.asarray(row[f.name]).astype(cur.dtype))
        fields["log"] = Log(buf=put(np.asarray(row["log_buf"],
                                               np.int32)))
        self.state = ReplicaState(**fields)

    def restore_hardstate(self, term: int, voted_term: int,
                          voted_for: int) -> None:
        """Install this host's persisted election state (HardState file)
        into its replica's state row — election safety across restarts: a
        recovered daemon must never re-grant a vote it already cast.
        Collective: every host calls this at the same point (pass zeros
        when it has no persisted state)."""
        g = self._global_from_local(
            np.array([term, voted_term, voted_for], np.int32))  # [R, 3]

        @jax.jit
        def upd(state, g):
            return dataclasses.replace(
                state,
                term=jnp.maximum(state.term, g[:, 0]),
                voted_for=jnp.where(g[:, 1] > state.voted_term,
                                    g[:, 2], state.voted_for),
                voted_term=jnp.maximum(state.voted_term, g[:, 1]),
            )
        self.state = upd(self.state, g)

    def _global_from_local(self, local: np.ndarray,
                           fill=0) -> jax.Array:
        """Build a [R, ...] global array where this host provides row
        ``me`` (other rows come from the other hosts). When several mesh
        devices are addressable by THIS process (single-process testing),
        the extra rows are filled with the field's NEUTRAL value ``fill``
        (0 = no input for batches/timeouts; peer_mask passes 1 — an
        all-zero mask would make those replicas deaf, not idle)."""
        shards = []
        for d in self.mesh.devices.flat:
            if d.process_index != jax.process_index():
                continue
            row = (local if d == self._local_dev
                   else np.full_like(local, fill))
            shards.append(jax.device_put(row[None], d))
        return jax.make_array_from_single_device_arrays(
            (self.R,) + local.shape, self._sharding, shards)

    def make_input(self, batch: Sequence[Tuple[int, int, int, bytes]] = (),
                   timeout_fired: bool = False,
                   apply_done: int = 0,
                   peer_mask: Optional[np.ndarray] = None,
                   gen: int = 0, queue_depth: int = 0) -> StepInput:
        cfg, B = self.cfg, self.cfg.batch_slots
        st = self._stage
        if st["dirty"]:
            st["data"][:st["dirty"]] = 0
            st["meta"][:st["dirty"]] = 0
        data, meta = st["data"], st["meta"]
        st["dirty"] = self._pack_batch(batch, data, meta, gen)
        if peer_mask is not None and self._fanout == "psum":
            # the psum fan-out is sound only under full connectivity: a
            # partition mask could leave two self-claimed leaders whose
            # windows SUM instead of being selected — reject loudly
            # rather than corrupt logs (use fanout="gather" to simulate
            # partitions)
            if not np.all(np.asarray(peer_mask) != 0):
                raise ValueError(
                    "psum fan-out requires an all-ones peer_mask; "
                    "build the driver with fanout='gather' to model "
                    "partitions")
        pm = (np.ones(self.R, np.int32) if peer_mask is None
              else peer_mask.astype(np.int32))
        return StepInput(
            batch_data=self._global_from_local(data),
            batch_meta=self._global_from_local(meta),
            batch_count=self._global_from_local(
                np.asarray(min(len(batch), B), np.int32)),
            timeout_fired=self._global_from_local(
                np.asarray(int(timeout_fired), np.int32)),
            peer_mask=self._global_from_local(pm, fill=1),
            apply_done=self._global_from_local(
                np.asarray(apply_done, np.int32)),
            queue_depth=self._global_from_local(
                np.asarray(queue_depth, np.int32)),
        )

    def _pack_batch(self, batch, data: np.ndarray, meta: np.ndarray,
                    gen: int) -> int:
        """Fill one [B, ...] data/meta pair from (etype, conn, req,
        payload) rows — the single packing used by steps AND bursts,
        delegated to the shared vectorized host data plane
        (``hostpath.pack_window``: one payload join + one scatter per
        window; all three drivers pack through the one batched
        implementation). Returns the number of rows written (the
        caller's dirty count; rows are assumed pre-zeroed)."""
        from rdma_paxos_tpu.runtime.hostpath import pack_window
        du8 = data.view(np.uint8).reshape(data.shape[0], -1)
        return pack_window(du8, meta, list(batch)[:data.shape[0]],
                           self.cfg.slot_bytes, gen=gen)

    def step(self, **kw) -> Dict[str, np.ndarray]:
        """One collective protocol step; every host must call this in the
        same loop iteration. Returns THIS replica's scalar outputs."""
        inp = self.make_input(**kw)
        self.state, out = self._step(self.state, inp)
        res = {}
        keys = OUT_KEYS + (("audit_start", "audit_digest",
                            "audit_term") if self._audit else ())
        for k in keys:
            arr = getattr(out, k)
            # a 1-wide replica axis (single-host world) shards as
            # slice(None), whose .start is None — that shard IS
            # replica 0's
            local = [s for s in arr.addressable_shards
                     if (s.index[0].start or 0) == self.me]
            res[k] = np.asarray(local[0].data[0]) if local else None
        return res

    def _kglobal(self, local_k: np.ndarray, fill=0) -> jax.Array:
        """[K, R, ...] global array sharded on axis 1; this host provides
        column ``me`` (other columns come from the other hosts)."""
        shards = []
        for d in self.mesh.devices.flat:
            if d.process_index != jax.process_index():
                continue
            col = (local_k if d == self._local_dev
                   else np.full_like(local_k, fill))
            shards.append(jax.device_put(col[:, None], d))
        return jax.make_array_from_single_device_arrays(
            (local_k.shape[0], self.R) + local_k.shape[1:],
            self._ksharding, shards)

    def _burst_fn(self):
        if self._burst is None:
            from rdma_paxos_tpu.parallel.mesh import build_spmd_burst
            self._burst = build_spmd_burst(
                self.cfg, self.R, self.mesh, fanout=self._fanout,
                audit=self._audit,
                use_pallas=jax.default_backend() == "tpu")
        return self._burst

    def step_burst(self, K: int,
                   batches: Sequence[Sequence[Tuple[int, int, int,
                                                    bytes]]] = (),
                   apply_done: int = 0, gen: int = 0,
                   queue_depth: int = 0) -> Dict[str, np.ndarray]:
        """K fused protocol steps in ONE collective dispatch. EVERY host
        must call this in the same iteration with the SAME K (derived
        from the gathered ``burst_hint`` — identical on all hosts under
        full connectivity; each distinct K is a separate compile, so
        drivers should stick to one K). ``batches``: up to K client
        batches for this host (empty on followers). ``queue_depth``:
        backlog REMAINING beyond this burst — it rides every burst
        step's gather so the final ``burst_hint`` sustains back-to-back
        bursts. No election timeouts fire inside a burst (each step
        carries the heartbeat). Returns this replica's final-step
        outputs plus ``accepted`` summed over the burst."""
        assert K > 0, K
        cfg, B = self.cfg, self.cfg.batch_slots
        st = self._kstage.get(K)
        if st is None:
            st = self._kstage[K] = dict(
                data=np.zeros((K, B, cfg.slot_words), np.int32),
                meta=np.zeros((K, B, META_W), np.int32),
                dirty=[0] * K)
        data, meta, dirty = st["data"], st["meta"], st["dirty"]
        for k, n in enumerate(dirty):
            if n:
                data[k, :n] = 0
                meta[k, :n] = 0
                dirty[k] = 0
        count = np.zeros((K,), np.int32)
        for k, batch in enumerate(list(batches)[:K]):
            dirty[k] = self._pack_batch(batch, data[k], meta[k], gen)
            count[k] = min(len(batch), B)
        fn = self._burst_fn()
        pm = self._global_from_local(np.ones(self.R, np.int32), fill=1)
        ap = self._global_from_local(np.asarray(apply_done, np.int32))
        qd = self._global_from_local(np.asarray(queue_depth, np.int32))
        self.state, outs = fn(self.state, self._kglobal(data),
                              self._kglobal(meta), self._kglobal(count),
                              pm, ap, qd)
        res = {}
        for k in OUT_KEYS:
            arr = getattr(outs, k)            # [K, R, ...]
            local = [s for s in arr.addressable_shards
                     if (s.index[1].start or 0) == self.me]
            res[k] = (np.asarray(local[0].data[-1, 0])
                      if local else None)
        if res["accepted"] is not None:
            acc = [s for s in outs.accepted.addressable_shards
                   if (s.index[1].start or 0) == self.me]
            res["accepted"] = np.asarray(acc[0].data[:, 0]).sum()
        if self._audit:
            # audit windows for EVERY fused step (not just the last) —
            # the daemon ingests them in order so the digest-chain
            # tiling holds through bursts; audit_commit carries the
            # matching per-step commit frontiers
            for k in ("audit_start", "audit_digest", "audit_term",
                      "commit"):
                arr = getattr(outs, k)          # [K, R, ...]
                local = [s for s in arr.addressable_shards
                         if (s.index[1].start or 0) == self.me]
                res["audit_commit" if k == "commit" else k] = (
                    np.asarray(local[0].data[:, 0]) if local else None)
        return res

    def _scan_fn(self):
        if self._scan is None:
            from rdma_paxos_tpu.parallel.mesh import build_spmd_scan
            self._scan = build_spmd_scan(
                self.cfg, self.R, self.mesh,
                replay_slots=self.cfg.window_slots,
                fanout=self._fanout, audit=self._audit,
                use_pallas=jax.default_backend() == "tpu")
        return self._scan

    def step_scan(self, K: int,
                  batches: Sequence[Sequence[Tuple[int, int, int,
                                                   bytes]]] = (),
                  apply_done: int = 0, gen: int = 0,
                  queue_depth: int = 0
                  ) -> Tuple[Dict[str, np.ndarray],
                             Tuple[np.ndarray, np.ndarray]]:
        """The K-window scan tier of :meth:`step_burst`: K fused
        protocol steps whose readback is ONE consolidated scalar
        matrix — plus this replica's replay window (``window_slots``
        committed rows from ``apply_done`` on, read from the POST-scan
        log inside the same dispatch), so the daemon's apply loop
        needs no per-window ``fetch_local_window`` dispatches for
        entries the scan already staged. Same collective-schedule
        contract as bursts: every host calls this in the same
        iteration with the same K. Returns ``(res, (wdata, wmeta))``;
        ``res`` matches :meth:`step_burst`'s (``accepted`` summed,
        audit windows per fused step when compiled)."""
        assert K > 0, K
        cfg, B = self.cfg, self.cfg.batch_slots
        st = self._kstage.get(K)
        if st is None:
            st = self._kstage[K] = dict(
                data=np.zeros((K, B, cfg.slot_words), np.int32),
                meta=np.zeros((K, B, META_W), np.int32),
                dirty=[0] * K)
        data, meta, dirty = st["data"], st["meta"], st["dirty"]
        for k, n in enumerate(dirty):
            if n:
                data[k, :n] = 0
                meta[k, :n] = 0
                dirty[k] = 0
        count = np.zeros((K,), np.int32)
        for k, batch in enumerate(list(batches)[:K]):
            dirty[k] = self._pack_batch(batch, data[k], meta[k], gen)
            count[k] = min(len(batch), B)
        fn = self._scan_fn()
        pm = self._global_from_local(np.ones(self.R, np.int32), fill=1)
        ap = self._global_from_local(np.asarray(apply_done, np.int32))
        qd = self._global_from_local(np.asarray(queue_depth, np.int32))
        self.state, outs = fn(self.state, self._kglobal(data),
                              self._kglobal(meta),
                              self._kglobal(count), pm, ap, qd)

        def local_of(arr, axis):
            sh = [s for s in arr.addressable_shards
                  if (s.index[axis].start or 0) == self.me]
            return sh[0].data if sh else None

        from rdma_paxos_tpu.consensus.step import SCAN_KEYS
        scal = local_of(outs["scal"], 1)        # [K, 1, NS]
        res: Dict[str, np.ndarray] = {}
        if scal is not None:
            row = np.asarray(scal[-1, 0])
            for i, k in enumerate(SCAN_KEYS):
                res[k] = row[i]
        else:
            res = {k: None for k in SCAN_KEYS}
        if self._audit and scal is not None:
            for k in ("audit_start", "audit_digest", "audit_term",
                      "audit_commit"):
                loc = local_of(outs[k], 1)      # [K, 1, ...]
                res[k] = (np.asarray(loc[:, 0]) if loc is not None
                          else None)
        wd = local_of(outs["replay_data"], 0)   # [1, W, sw]
        wm = local_of(outs["replay_meta"], 0)
        rows = (np.asarray(wd[0]) if wd is not None else None,
                np.asarray(wm[0]) if wm is not None else None)
        return res, rows

    def rebase(self, delta: int) -> None:
        """Apply the coordinated i32-offset rollover to this host's
        sharded state (see ``consensus/snapshot.rebase_offsets``). The
        program is purely elementwise — no collectives — so hosts may
        apply it independently once they agree on ``delta`` (the step's
        gathered ``rebase_delta`` output, identical on every host under
        full connectivity)."""
        from rdma_paxos_tpu.consensus.snapshot import rebase_offsets
        self.state = rebase_offsets(
            self.state, jnp.asarray(delta, jnp.int32))

    def export_local_row(self) -> dict:
        """THIS replica's full state row as host numpy (local shard reads
        only — no collective), keyed like ``snapshot.export_row``. The
        donor half of elastic world rebuild."""
        import dataclasses as _dc
        from rdma_paxos_tpu.consensus.state import ReplicaState

        def local(arr):
            sh = [s for s in arr.addressable_shards
                  if (s.index[0].start or 0) == self.me]
            return np.asarray(sh[0].data[0])

        out = {"log_buf": local(self.state.log.buf)}
        for f in _dc.fields(ReplicaState):
            if f.name != "log":
                out[f.name] = local(getattr(self.state, f.name))
        return out

    def fetch_local_window(self, start: int
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """Read ``window_slots`` entries beginning at ``start`` from THIS
        replica's log. Host-local (no collective): call freely, on any
        host, only when needed."""
        sh = [s for s in self.state.log.buf.addressable_shards
              if (s.index[0].start or 0) == self.me][0]
        wd, wm = self._local_fetch(sh.data[0],
                                   jnp.asarray(start, jnp.int32))
        return np.asarray(wd), np.asarray(wm)
