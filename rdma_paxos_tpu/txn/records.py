"""Log-record wire format of the 2PC commit lane.

Transaction records ride SEND entries through the SAME replicated log
as KVS commands, but at a DISTINCT payload width — the state-machine
fold dispatches on width, so a legacy fold (or any non-KVS consumer)
skips them without decoding. Layout (int32 words, little-endian):

    [txn_op][tid][arg][kvs_cmd CMD_W words]        TXN_CMD_W = 20

* ``PREPARE``: ``arg`` = 0; the embedded ``kvs_cmd`` is ONE staged
  write of transaction ``tid`` on this group. The fold BUFFERS it per
  tid — nothing touches the table until the commit record lands, so
  an aborted transaction leaves no partial writes by construction.
* ``COMMIT``: ``arg`` = the participant-group bitmask (G <= 32 — the
  strict-serializability checker's atomicity witness: a commit seen
  in one group's log must appear in every masked group's log);
  embedded command unused. The fold applies ``tid``'s buffered writes
  in staging order, then drops the buffer.
* ``ABORT``: ``arg`` = an abort-reason code (host telemetry only);
  the fold drops the buffer unapplied.
* ``MERGE``: one mergeable fast-path write (txn/merge.py); ``arg`` =
  how many merge records its transaction submits to THIS group. The
  fold applies the embedded command immediately (commutative — no
  staging) and retires the tid's dedup memory once all ``arg``
  records have folded, so the fast path stays coordination-free AND
  leaves no per-record registry residue.

Exactly-once for ALL of these is per tid, not per session: every
record's ``(conn, req)`` stamp is unique, the fold remembers only the
reqs of live tids, and a tid's memory is dropped with its decision
(or last merge record) — see ``ReplicatedKVS._fold_txn``.
"""

from __future__ import annotations

import numpy as np

from rdma_paxos_tpu.models.kvs import CMD_W, encode_cmd

TXN_PREPARE, TXN_COMMIT, TXN_ABORT, TXN_MERGE = 1, 2, 3, 4
TXN_CMD_W = 3 + CMD_W

# ABORT-record reason codes (mirrors the txn_aborted_total labels).
# TOPOLOGY: the key→group mapping of a participant key moved while the
# transaction was in flight (an elastic split/merge cutover bumped the
# router epoch) — locking or committing against the stale group would
# write state the new routing never serves, so the coordinator aborts
# deterministically instead.
ABORT_CONFLICT, ABORT_TIMEOUT, ABORT_FAILOVER, ABORT_TOPOLOGY = 1, 2, 3, 4


def encode_prepare(tid: int, op: int, key: bytes,
                   val: bytes = b"") -> bytes:
    """One staged write of ``tid`` (this group's share of the txn)."""
    return np.concatenate([
        np.array([TXN_PREPARE, tid, 0], "<i4"),
        encode_cmd(op, key, val)]).astype("<i4").tobytes()


def encode_commit(tid: int, participant_mask: int) -> bytes:
    return np.concatenate([
        np.array([TXN_COMMIT, tid, participant_mask], "<i4"),
        np.zeros(CMD_W, "<i4")]).astype("<i4").tobytes()


def encode_abort(tid: int, reason: int) -> bytes:
    return np.concatenate([
        np.array([TXN_ABORT, tid, reason], "<i4"),
        np.zeros(CMD_W, "<i4")]).astype("<i4").tobytes()


def encode_merge(tid: int, n_of: int, op: int, key: bytes,
                 val: bytes = b"") -> bytes:
    """One mergeable fast-path write of ``tid`` on this group;
    ``n_of`` = the transaction's total merge-record count here (the
    fold's retire trigger)."""
    return np.concatenate([
        np.array([TXN_MERGE, tid, n_of], "<i4"),
        encode_cmd(op, key, val)]).astype("<i4").tobytes()


def decode_record(payload: bytes):
    """``(txn_op, tid, arg, kvs_cmd_words)`` of a TXN_CMD_W payload."""
    words = np.frombuffer(payload, "<i4")
    return (int(words[0]), int(words[1]), int(words[2]), words[3:])
