"""Mergeable-op fast path — SafarDB-style coordination-free commits.

A cross-group transaction whose writes are ALL mergeable needs no
prepare phase: each op is a commutative, associative fold into the
current value (``models/kvs.py`` ops 4-6), so per-group entries commit
independently in ANY interleaving and converge to the same state — the
replicated-data-type argument of SafarDB (arXiv:2603.08003). The
coordinator detects this shape and submits one stamped MERGE record
per write (``txn/records.py``) instead of the PREPARE/COMMIT record
pair; the fold applies a MERGE the moment it commits — no staging, no
votes. Atomicity demotes to eventual all-or-nothing via the retry
rule (every record is retried under its original ``(conn, req)``
until committed, deduped per tid by the fold), which is exactly the
guarantee merges need — there is no intermediate state a reader could
tear.

Host-side helpers only — device folds live in ``models/kvs.py``.
"""

from __future__ import annotations

import struct
from typing import Dict, Tuple

from rdma_paxos_tpu.models.kvs import OP_INCR, OP_MAX, OP_SADD, VAL_W

#: op name -> (op code, host fold used by tests/bench to predict state)
MERGE_FNS: Dict[str, Tuple[int, object]] = {
    "incr": (OP_INCR, lambda a, b: a + b),
    "sadd": (OP_SADD, lambda a, b: a | b),
    "max": (OP_MAX, max),
}

_MERGE_OPS = frozenset(code for code, _ in MERGE_FNS.values())


def is_mergeable(op: int) -> bool:
    return op in _MERGE_OPS


def encode_merge_val(op: int, value: int) -> bytes:
    """Pack a host integer operand into value words. The device folds
    are per-i32-LANE (``base + val`` elementwise, no carry between
    words), so INCR/MAX operands are a signed i32 in word 0 only; SADD
    sets one bit (``value`` mod the 256 value bits) of the lane
    bitset."""
    if op == OP_SADD:
        bit = value % (VAL_W * 32)
        words = [0] * VAL_W
        words[bit // 32] = 1 << (bit % 32)
        return struct.pack(f"<{VAL_W}i", *[
            w - (1 << 32) if w >= (1 << 31) else w for w in words])
    return struct.pack("<i", value) + b"\x00" * ((VAL_W - 1) * 4)


def decode_merge_val(op: int, raw: bytes) -> int:
    """Inverse of :func:`encode_merge_val` over a table read: the i32
    lane-0 counter value, or the popcount of the SADD bitset."""
    buf = raw.ljust(VAL_W * 4, b"\x00")
    if op == OP_SADD:
        return bin(int.from_bytes(buf, "little", signed=False)).count("1")
    return struct.unpack_from("<i", buf)[0]


def mergeable_plan(writes) -> bool:
    """True when EVERY write of a transaction is mergeable — the
    coordinator's fast-path admission test. ``writes`` is the
    transact() write set: ``(op, key, val_bytes)`` triples."""
    return bool(writes) and all(is_mergeable(op) for op, _k, _v in writes)
