"""Txn nemesis — coordinator-leader crash mid-prepare, proven atomic.

The shard nemesis (:mod:`rdma_paxos_tpu.shard.chaos`) proves faults
stay inside their group; the txn nemesis proves the NEW cross-group
claims survive the same fault. It drives a ``txn=True``
:class:`~rdma_paxos_tpu.shard.cluster.ShardedCluster` with a mixed
workload — single-key session puts (per-key Wing–Gong history),
2PC cross-group transactions on fresh key pairs, mergeable INCR
transactions on per-group counters — then fail-stops the leader of the
target group EXACTLY while a 2PC transaction's PREPAREs are in flight
to it, re-elects, heals, settles, and verdicts:

* **strict serializability** over the per-group committed streams
  (:func:`~rdma_paxos_tpu.chaos.serialize.check_txn_streams`):
  commit atomicity against the participant masks, no commit+abort
  tids, acyclic cross-group precedence;
* **no partial writes**: every aborted transaction's (key, unique
  value) pairs are invisible everywhere; every committed one's are
  visible (fresh keys per txn — nothing overwrites them);
* **mergeable convergence**: each group's counter lands between the
  committed and attempted INCR sums (undecided tail may or may not
  have folded — exactly the retransmit-until-committed contract);
* the existing bars stay green: per-group I1–I5 invariants +
  convergence, and the single-key Wing–Gong history;
* the crash-straddling transaction **aborts deterministically**
  (failover or step-domain timeout — never a partial commit).

Determinism: all randomness derives from the seed; time is the
logical step counter — same seed, same verdict.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from rdma_paxos_tpu.chaos.faults import LinkModel
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history
from rdma_paxos_tpu.chaos.runner import DEFAULT_KV_CFG
from rdma_paxos_tpu.chaos.serialize import check_txn_streams
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.models.kvs import OP_INCR
from rdma_paxos_tpu.shard.chaos import keys_for_groups
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS
from rdma_paxos_tpu.txn.coordinator import attach_coordinator
from rdma_paxos_tpu.txn.merge import decode_merge_val


class TxnNemesisRunner:
    """One seeded coordinator-leader-crash run over a fresh txn=True
    sharded cluster."""

    def __init__(self, cfg: Optional[LogConfig] = None,
                 n_replicas: int = 3, n_groups: int = 3, *,
                 seed: int = 0, steps: int = 48, crash_step: int = 16,
                 reelect_after: int = 4, target_group: int = 0,
                 settle_steps: int = 20, txn_every: int = 4,
                 timeout_steps: int = 12, obs=None):
        self.cfg = cfg or DEFAULT_KV_CFG
        self.R, self.G = int(n_replicas), int(n_groups)
        self.seed = int(seed)
        self.steps = int(steps)
        self.crash_step = int(crash_step)
        self.reelect_after = int(reelect_after)
        self.target = int(target_group)
        self.settle_steps = int(settle_steps)
        self.txn_every = int(txn_every)
        self.shard = ShardedCluster(self.cfg, self.R, self.G, txn=True)
        if obs is None:
            from rdma_paxos_tpu.obs import Observability
            obs = Observability()
        self.obs = obs
        self.shard.obs = obs
        self.kv = ShardedKVS(self.shard, cap=256)
        self.coord = attach_coordinator(self.kv,
                                        timeout_steps=timeout_steps)
        self.link = LinkModel(self.R, seed=seed)
        self.shard.link_models[self.target] = self.link
        self.checkers = [InvariantChecker(self.R)
                         for _ in range(self.G)]
        # key pools: session keys (reused, Wing–Gong checked), fresh
        # 2PC keys (one per txn per group — visibility is unambiguous),
        # one counter key per group (mergeable INCR target)
        n_txn = self.steps // max(1, self.txn_every) + 2
        self.keys = keys_for_groups(self.kv.router, 2)
        self.txn_keys = keys_for_groups(self.kv.router, n_txn,
                                        prefix=b"txk")
        self.ctr_keys = [ks[0] for ks in
                         keys_for_groups(self.kv.router, 1,
                                         prefix=b"ctr")]
        self._txn_used = [0] * self.G
        self.rng = random.Random(f"txn-nemesis:{seed}")
        self._vn = 0
        self.history = HistoryRecorder()
        for g in range(self.G):
            self.kv.groups[g].history = self.history
        self.sess = self.kv.session(1)
        self._out: List[Optional[dict]] = [None] * self.G
        self.write_patience = 14
        # launched transactions: (handle, kind, {key: val}|{g: incr})
        self.launched: List[dict] = []
        self._merge_attempt = [0] * self.G

    # ------------------------------------------------------------------

    def _fresh_pair(self, ga: int, gb: int):
        ka = self.txn_keys[ga][self._txn_used[ga]]
        kb = self.txn_keys[gb][self._txn_used[gb]]
        self._txn_used[ga] += 1
        self._txn_used[gb] += 1
        return ka, kb

    def _launch_txn(self, t: int, idx: int) -> None:
        """Alternate 2PC put-pairs and mergeable INCR pairs across a
        rotating pair of groups — every launch is recorded with its
        expected effect for the post-run visibility audit."""
        ga, gb = idx % self.G, (idx + 1) % self.G
        if ga == gb:
            gb = (gb + 1) % self.G
        if idx % 2 == 0:
            ka, kb = self._fresh_pair(ga, gb)
            va, vb = b"T%d.a" % idx, b"T%d.b" % idx
            h = self.kv.transact([("put", ka, va), ("put", kb, vb)])
            self.launched.append(dict(handle=h, kind="2pc",
                                      writes={ka: va, kb: vb},
                                      launched_at=t))
        else:
            h = self.kv.transact([("incr", self.ctr_keys[ga], 1),
                                  ("incr", self.ctr_keys[gb], 1)])
            self._merge_attempt[ga] += 1
            self._merge_attempt[gb] += 1
            self.launched.append(dict(handle=h, kind="merge",
                                      groups=(ga, gb), launched_at=t))

    def _crash_straddler(self, t: int) -> None:
        """THE scenario: a 2PC transaction with the target group as a
        participant, admitted the same step its leader fail-stops —
        its PREPARE is in flight to a replica that never answers."""
        gb = (self.target + 1) % self.G
        ka, kb = self._fresh_pair(self.target, gb)
        h = self.kv.transact([("put", ka, b"straddle.a"),
                              ("put", kb, b"straddle.b")])
        self.launched.append(dict(handle=h, kind="straddler",
                                  writes={ka: b"straddle.a",
                                          kb: b"straddle.b"},
                                  launched_at=t))

    def _issue(self, t: int) -> None:
        """Closed-loop session write per group (the shard nemesis'
        client contract: one outstanding, retransmit-on-failover,
        patience→ambiguous)."""
        for g in range(self.G):
            lead = self.shard.leader_hint(g)
            out = self._out[g]
            if out is not None:
                if t - out["issued"] > self.write_patience:
                    self.history.timeout(out["op_id"])   # fate unknown
                    self._out[g] = None
                elif lead >= 0 and lead != out["to"]:
                    out["to"] = lead
                    self.sess.retransmit_put(out["key"], out["val"],
                                             out["req_id"],
                                             leader=lead)
                out = self._out[g]
            if out is None and lead >= 0:
                key = self.rng.choice(self.keys[g])
                self._vn += 1
                val = b"v%d" % self._vn
                _, rid = self.sess.put(key, val, leader=lead)
                op_id = self.history.op_id_for(
                    self.sess.conn_for(g), rid)
                self._out[g] = dict(key=key, val=val, req_id=rid,
                                    op_id=op_id, to=lead, issued=t)

    def _observe_clients(self, t: int) -> None:
        for g in range(self.G):
            out = self._out[g]
            if out is None:
                continue
            lead = self.shard.leader_hint(g)
            if lead < 0:
                continue
            self.kv.groups[g]._fold(lead)
            marks = self.kv.groups[g].last_req[lead]
            if marks.get(self.sess.conn_for(g), 0) >= out["req_id"]:
                self.history.ok(out["op_id"])
                self._out[g] = None

    def _check(self, res, t: int, violations: List[dict]) -> None:
        for g in range(self.G):
            try:
                self.checkers[g].check_step(
                    {k: res[k][g] for k in ("commit", "role", "term",
                                            "head", "apply", "end")},
                    step=t,
                    rebased_total=int(self.shard.rebased_total[g]))
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)

    def _audit_effects(self) -> List[dict]:
        """Post-settle visibility audit: committed 2PC writes visible,
        aborted/undecided ones invisible — on FRESH keys, so there is
        no overwrite ambiguity (no partial writes, directly)."""
        bad: List[dict] = []
        for rec in self.launched:
            if rec["kind"] == "merge":
                continue
            h = rec["handle"]
            for key, val in rec["writes"].items():
                got = self.kv.get(key)
                if h.committed and got != val:
                    bad.append(dict(kind="committed_write_missing",
                                    tid=h.tid, key=key.decode()))
                if not h.committed and got == val:
                    bad.append(dict(kind="partial_write_visible",
                                    tid=h.tid, key=key.decode(),
                                    state=h.state))
        return bad

    def _merge_summary(self) -> Dict:
        """Per-group counter value vs the committed / attempted INCR
        sums — the mergeable fast path's convergence window."""
        committed = [0] * self.G
        for rec in self.launched:
            if rec["kind"] == "merge" and rec["handle"].committed:
                for g in rec["groups"]:
                    committed[g] += 1
        values, ok = [], True
        for g in range(self.G):
            raw = self.kv.get(self.ctr_keys[g])
            v = decode_merge_val(OP_INCR, raw) if raw else 0
            values.append(v)
            if not (committed[g] <= v <= self._merge_attempt[g]):
                ok = False
        return dict(ok=ok, values=values, committed=committed,
                    attempted=list(self._merge_attempt))

    def run(self) -> Dict:
        violations: List[dict] = []
        self.shard.place_leaders()
        crashed = -1
        timeouts: Dict[int, list] = {}
        for t in range(self.steps):
            self.history.set_clock(t)
            timeouts = {}
            if t == self.crash_step:
                self._crash_straddler(t)
                crashed = self.shard.leader_hint(self.target)
                self.link.down.add(crashed)     # fail-stop, silent
            elif (t % self.txn_every == 0
                    and t < self.steps - self.txn_every):
                self._launch_txn(t, t // self.txn_every)
            if crashed >= 0 and t == self.crash_step + self.reelect_after:
                cand = next(r for r in range(self.R) if r != crashed)
                timeouts[self.target] = [cand]
            self._issue(t)
            res = self.shard.step(timeouts=timeouts)
            self._observe_clients(t)
            self._check(res, t, violations)
        self.link.down.discard(crashed)
        self.link.heal()
        for t in range(self.steps, self.steps + self.settle_steps):
            self.history.set_clock(t)
            self._issue(t)
            res = self.shard.step()
            self._observe_clients(t)
            self._check(res, t, violations)
        self.history.set_clock(self.steps + self.settle_steps)
        for op_id in self.history.pending():
            self.history.timeout(op_id)
        for g in range(self.G):
            try:
                self.checkers[g].check_convergence(
                    self.shard.replayed[g])
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)
        # strict serializability straight off the committed evidence:
        # per group, the longest replica stream (committed prefixes of
        # a converged group agree — length only differs by lag)
        streams = [max(self.shard.replayed[g], key=len)
                   for g in range(self.G)]
        ser = check_txn_streams(streams)
        effects = self._audit_effects()
        merge = self._merge_summary()
        linz = check_history(self.history.ops())
        straddler = next(r["handle"] for r in self.launched
                         if r["kind"] == "straddler")
        txns = dict(
            launched=len(self.launched),
            committed=sum(r["handle"].committed
                          for r in self.launched),
            aborted=sum(r["handle"].done
                        and not r["handle"].committed
                        for r in self.launched),
            undecided=sum(not r["handle"].done
                          for r in self.launched),
            abort_reasons=sorted({r["handle"].abort_reason
                                  for r in self.launched
                                  if r["handle"].done
                                  and not r["handle"].committed
                                  and r["handle"].abort_reason}),
            straddler=dict(state=straddler.state,
                           reason=straddler.abort_reason))
        new_leader = self.shard.leader_hint(self.target)
        ok = (not violations and ser["ok"] and not effects
              and merge["ok"] and linz["ok"] is True
              and txns["undecided"] == 0
              and straddler.done and not straddler.committed
              and new_leader >= 0 and new_leader != crashed)
        return dict(
            ok=ok, seed=self.seed, steps=self.steps,
            target_group=self.target, crashed_leader=crashed,
            new_leader=new_leader,
            invariant_violations=violations,
            serializability=ser,
            effect_violations=effects,
            merge=merge,
            linearizability=dict(ok=linz["ok"],
                                 violations=linz["violations"],
                                 undecided=linz["undecided"],
                                 ops=linz["ops"]),
            txns=txns,
            coordinator=self.coord.health(),
        )


def run_txn_chaos(seed: int = 0, **kw) -> Dict:
    """One seeded txn-nemesis run; same seed, same verdict."""
    return TxnNemesisRunner(seed=seed, **kw).run()
