"""Client surface of the transaction subsystem.

``transact()`` (also exposed as ``ShardedKVS.transact``) admits one
multi-key transaction against the attached coordinator and returns a
:class:`TxnHandle`. Ops are named strings mapped to the state-machine
op codes — plain writes (``put``/``rm``) take the 2PC commit lane;
mergeable writes (``incr``/``sadd``/``max``) with integer operands
take the coordination-free fast path when the WHOLE write set is
mergeable. Exactly-once rides the coordinator's stamped ``(conn,
req)`` records — a retried record commits at most once per group, the
same session dedup rule every client write already obeys.

The handle is asynchronous: the coordinator advances off the cluster's
finish() tail, so callers pump protocol steps (or run under a driver)
and poll ``handle.done`` / call ``handle.wait(pump)``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from rdma_paxos_tpu.models.kvs import OP_PUT, OP_RM
from rdma_paxos_tpu.txn import merge as _merge

_NAMED_OPS = {"put": OP_PUT, "rm": OP_RM}


class TxnHandle:
    """Client view of one admitted transaction."""

    def __init__(self, txn):
        self._txn = txn

    @property
    def tid(self) -> int:
        return self._txn.tid

    @property
    def state(self) -> str:
        return self._txn.state

    @property
    def done(self) -> bool:
        return self._txn.done

    @property
    def committed(self) -> bool:
        return self._txn.committed

    @property
    def abort_reason(self) -> Optional[str]:
        return self._txn.reason

    @property
    def reads(self) -> dict:
        """Read-set values fetched at the serialization point (commit
        decision time, under the participant locks)."""
        return dict(self._txn.reads)

    def wait(self, pump, max_steps: int = 256) -> bool:
        """Drive ``pump()`` (one protocol step) until the transaction
        decides; returns ``committed``. Raises after ``max_steps``
        pumps without a decision."""
        for _ in range(max_steps):
            if self.done:
                return self.committed
            pump()
        if not self.done:
            raise TimeoutError(
                f"txn {self.tid} undecided after {max_steps} pumps "
                f"(state={self.state})")
        return self.committed


def _encode_write(op_name: str, key: bytes, val) -> Tuple[int, bytes,
                                                          bytes]:
    op = _NAMED_OPS.get(op_name)
    if op is not None:
        return op, key, (val if isinstance(val, bytes) else b"")
    entry = _merge.MERGE_FNS.get(op_name)
    if entry is None:
        raise ValueError(f"unknown txn op {op_name!r}")
    code = entry[0]
    if isinstance(val, bytes):
        return code, key, val
    return code, key, _merge.encode_merge_val(code, int(val))


def transact(kvs, writes: Sequence[Tuple[str, bytes, object]],
             reads: Sequence[bytes] = ()) -> TxnHandle:
    """Admit one transaction on ``kvs`` (a ShardedKVS whose cluster
    has a coordinator attached). ``writes`` are ``(op_name, key,
    value)`` triples — op_name in {put, rm, incr, sadd, max}; integer
    values of mergeable ops are packed automatically. ``reads`` are
    keys whose values are captured at the serialization point."""
    coord = getattr(kvs.shard, "txn", None)
    if coord is None:
        raise RuntimeError(
            "no coordinator attached — call "
            "txn.attach_coordinator(kvs) first (requires a txn=True "
            "cluster)")
    encoded = [_encode_write(name, key, val)
               for name, key, val in writes]
    return TxnHandle(coord.begin(encoded, reads))
