"""Device-side commit lane of the cross-group transaction subsystem.

The host 2PC coordinator (``txn/coordinator.py``) appends one PREPARE
record per participant group and then needs each group's verdict:
*did my prepare become durable under the term I appended it in, or did
a leader change overwrite it?* Because all G groups advance in ONE
compiled dispatch (``group_step`` / the spmd mesh), that verdict is
computed *inside* the dispatch — each replica evaluates a per-group
watch ``(index, term)`` against its own post-absorb log and reports a
small vote scalar. The coordinator reads the stacked ``[G, R]`` vote
matrix from the very dispatch that replicated the prepares, so a
cross-group commit resolves in ~2 protocol steps instead of a host
round-trip per 2PC phase.

This module is device-pure by construction (jnp only — it is listed in
the static-analysis ``DEVICE_MODULES`` set) and is the ONLY txn module
``consensus/step.py`` may import: the host state machine, locks, and
API live behind the lazy ``txn/__init__`` and never reach jitted code.
"""

from __future__ import annotations

import jax.numpy as jnp

# Prepare-vote values, reported per (group, replica) when the ``txn=``
# step variant is compiled. The coordinator treats CONFLICT as
# dominant, then PREPARED, else PENDING (NONE rows carry no watch).
TXN_NONE = 0       # no watch armed for this group
TXN_PENDING = 1    # prepare appended but not yet committed
TXN_PREPARED = 2   # prepare durable: committed under the watched term
TXN_CONFLICT = 3   # index committed under a DIFFERENT term (the
                   # prepare was overwritten by a failover leader)


def prepare_vote(*, watch: jnp.ndarray, watch_term: jnp.ndarray,
                 head: jnp.ndarray, commit: jnp.ndarray,
                 entry_term: jnp.ndarray,
                 entry_gidx: jnp.ndarray) -> jnp.ndarray:
    """One replica's prepare vote for its group's armed watch.

    ``watch`` is the prepare entry's log offset (-1 = no watch armed);
    ``entry_term``/``entry_gidx`` are the meta columns of the slot the
    watch maps to in THIS replica's post-absorb log. A watch below the
    prune head votes PREPARED: pruning follows the host apply cursor,
    so a pruned index was committed and replayed — and the state-
    machine fold's per-tid record check is the backstop for the
    (coordinator-abort-covered) case where a failover overwrote the
    index before it committed.
    """
    vote = jnp.where(
        watch < head, TXN_PREPARED,
        jnp.where(
            (entry_gidx == watch) & (entry_term == watch_term)
            & (watch < commit), TXN_PREPARED,
            jnp.where(watch < commit, TXN_CONFLICT, TXN_PENDING)))
    return jnp.where(watch < 0, TXN_NONE, vote).astype(jnp.int32)
