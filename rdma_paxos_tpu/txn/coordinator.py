"""Host 2PC coordinator over the in-dispatch commit lane.

The classic coordinator pays a network round-trip per 2PC phase. Here
every group advances in ONE compiled dispatch, so the phases collapse
onto the dispatch cadence:

* **prepare** — one PREPARE record per staged write is submitted to
  each participant group's leader (stamped ``(conn, req)``, the
  session exactly-once rule). The dispatch that replicates them also
  evaluates each group's armed prepare watch (``txn/lane.py``) and
  reports the stacked ``[G, R]`` vote matrix in the SAME readback.
* **decide** — a PREPARED vote from any replica is definitive (the
  vote rule requires the watched index be COMMITTED under the watched
  term, i.e. majority-replicated); a CONFLICT vote is a definitive
  overwrite-under-failover. All groups prepared ⟹ COMMIT records are
  submitted; the next dispatch replicates them. Hence a cross-group
  commit costs ~2 protocol dispatches end to end.
* **abort** — deterministic, host-decided: step-domain timeout, lock
  conflict at admission, or participant-leader deposition (observed
  from the step outputs — the same signal the drivers' failover hooks
  key on). ABORT records release the groups' staged buffers; until a
  decision record commits, NOTHING touches any table
  (``models/replicated_kvs.py`` stages per tid), so aborted
  transactions leave no partial writes by construction.

Mergeable-only transactions (``txn/merge.py``) skip all of the above:
their writes commit as independent per-group MERGE records, applied
the moment they fold (no staging, no votes, no decision round).

Concurrency: participant locks are keyed ``(group, key)`` — a
conflicting admission aborts immediately (no waiting ⟹ no deadlock).
The commit lane arms ONE watch per group, so 2PC transactions admit
serially (queued FIFO); mergeable transactions never queue.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from rdma_paxos_tpu.topology import epoch as _epoch
from rdma_paxos_tpu.txn import merge as _merge
from rdma_paxos_tpu.txn import records as _records
from rdma_paxos_tpu.txn.lane import TXN_CONFLICT, TXN_PREPARED

# txn states
PREPARING = "preparing"      # prepare records out, votes pending
COMMITTING = "committing"    # commit records out, awaiting commit
ABORTING = "aborting"        # abort records out, awaiting commit
COMMITTED = "committed"      # terminal
ABORTED = "aborted"          # terminal
MERGING = "merging"          # fast path: merge commands out


class Txn:
    """One transaction's host bookkeeping (coordinator-internal; the
    client-facing view is :class:`rdma_paxos_tpu.txn.api.TxnHandle`)."""

    def __init__(self, tid: int, writes_by_group: Dict[int, list],
                 read_keys: Sequence[bytes], deadline: int,
                 fast: bool):
        self.tid = tid
        self.writes_by_group = writes_by_group
        self.read_keys = list(read_keys)
        self.deadline = deadline
        self.fast = fast
        self.state = MERGING if fast else PREPARING
        self.reason: Optional[str] = None
        # per-group: prepares appended so far / (index, term) of the
        # LAST appended prepare (the group's watch target)
        self.prep_appended: Dict[int, int] = {}
        self.watch: Dict[int, Tuple[int, int]] = {}
        # groups whose watch was armed THIS finish (note_appends runs
        # in the stamp loop, observe at the tail — same result dict):
        # eligible for same-finish host resolution
        self.watch_fresh: Dict[int, bool] = {}
        self.prepared: set = set()
        # decision/merge records: (g, req) -> absolute index once
        # appended (-1 = submitted, not yet appended)
        self.record_index: Dict[Tuple[int, int], int] = {}
        # term the record was appended under — a placement is only
        # proof of commit while the group's term is unchanged
        self.record_term: Dict[Tuple[int, int], int] = {}
        self.record_payload: Dict[Tuple[int, int], bytes] = {}
        # (g, req) -> step of the last (re)submission: decided records
        # are retried with patience until appended (dedup keeps the
        # retries exactly-once), surviving leader failover
        self.record_retry: Dict[Tuple[int, int], int] = {}
        self.reads: Dict[bytes, Optional[bytes]] = {}
        # trace-plane bookkeeping: the txn-level trace id (None when
        # tracing is off), the (group, req) keys of every record span
        # this txn opened and has not yet closed (prepare + decision/
        # merge — the coordinator OWNS their closure; before PR 20
        # they leaked open), and the per-group prepare reqs so a
        # group's prepare spans close the moment it votes PREPARED
        self.trace_id: Optional[str] = None
        self.span_keys: set = set()
        self.prep_reqs: Dict[int, List[int]] = {}
        # routing snapshot at admission: the router version the
        # key→group mapping was computed under, and every (group, key)
        # placement it produced — an elastic cutover bumps the version
        # and the coordinator aborts any undecided txn whose placement
        # moved (reason ``topology``) rather than lock/commit against
        # a group the new routing never serves
        self.router_version = 0
        self.admitted: List[Tuple[int, bytes]] = []

    @property
    def groups(self) -> Sequence[int]:
        return sorted(self.writes_by_group)

    @property
    def done(self) -> bool:
        return self.state in (COMMITTED, ABORTED)

    @property
    def committed(self) -> bool:
        return self.state == COMMITTED

    def participant_mask(self) -> int:
        mask = 0
        for g in self.writes_by_group:
            mask |= 1 << g
        return mask


class TxnCoordinator:
    """Attached to a :class:`~rdma_paxos_tpu.shard.kvs.ShardedKVS`
    (``attach_coordinator``): drives begin/prepare/commit/abort off the
    cluster's finish() tail — ``note_appends`` learns each record's
    ``(term, index)`` from the stamp loop, ``observe`` reads the vote
    matrix, advances timeouts, and detects participant deposition."""

    def __init__(self, kvs, *, client_id: int = 1 << 20,
                 timeout_steps: int = 64):
        self.kvs = kvs
        self.cluster = kvs.shard
        self.G = self.cluster.G
        if not getattr(self.cluster, "_txn", False):
            raise ValueError(
                "attach_coordinator requires a txn=True cluster "
                "(the commit lane rides the txn= step variant)")
        self.client_id = client_id
        self.timeout_steps = int(timeout_steps)
        self.committed_total = 0
        self.aborted_total: Dict[str, int] = collections.Counter()
        # ---- coordinator-lock discipline (runtime_guard-checked) ----
        # participant locks: (group, key) -> owning tid
        # guarded-by: _lock [writes]
        self._locks: Dict[Tuple[int, bytes], int] = {}
        # live transactions by tid  # guarded-by: _lock [writes]
        self._txns: Dict[int, Txn] = {}
        # (group, req) -> tid for in-flight stamped records
        # guarded-by: _lock [writes]
        self._outstanding: Dict[Tuple[int, int], int] = {}
        # FIFO of admitted-but-waiting 2PC txns (one armed watch per
        # group ⟹ serial 2PC)  # guarded-by: _lock [writes]
        self._queue: collections.deque = collections.deque()
        # the 2PC txn currently owning the commit lane (or None)
        # guarded-by: _lock [writes]
        self._active_2pc: Optional[int] = None
        # per-group stamped-request counter  # guarded-by: _lock [writes]
        self._req = [0] * self.G
        # per-group term each leader was last seen under (deposition
        # detection — the shared epoch machinery, one copy for txn AND
        # topology)  # guarded-by: _lock [writes]
        self._terms = _epoch.TermWatch(self.G)
        self._next_tid = 1                  # guarded-by: _lock [writes]
        self._lock = threading.RLock()
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    # ---------------- trace plane ----------------

    def _tracer(self):
        """The cluster's TraceContext iff tracing is enabled. Safe to
        call (and to use) under ``_lock``: the trace store is
        leaf-locked and this coordinator NEVER takes the topology
        controller's lock (drive() holds that lock while calling our
        ``wants_serial`` — the reverse order would deadlock ABBA; the
        window-trace handoff below is a lock-free attribute read)."""
        from rdma_paxos_tpu.obs.tracectx import active_tracer
        return active_tracer(getattr(self.cluster, "obs", None))

    # holds-lock: _lock
    def _close_record_spans(self, txn: Txn, keys, *, ok: bool,
                            status: str = "aborted") -> None:
        """Close record spans this txn opened — DONE when the record
        reached its outcome, else a terminal status carrying the abort
        reason (the fail_open discipline from runtime/node.py: spans
        terminate, never leak)."""
        from rdma_paxos_tpu.obs.spans import active_recorder
        spans = active_recorder(getattr(self.cluster, "obs", None))
        for (g, req) in list(keys):
            if spans is not None:
                if ok:
                    spans.ack_key(self._conn(g, req), req)
                else:
                    spans.fail_key(self._conn(g, req), req,
                                   status=status)
            txn.span_keys.discard((g, req))

    # holds-lock: _lock
    def _close_prep_spans(self, txn: Txn, g: int) -> None:
        self._close_record_spans(
            txn, [(g, r) for r in txn.prep_reqs.get(g, ())], ok=True)

    # ---------------- admission ----------------

    def begin(self, writes: Sequence[Tuple[int, bytes, bytes]],
              reads: Sequence[bytes] = ()) -> Txn:
        """Admit a transaction: ``writes`` are ``(op, key, val)``
        triples (op = OP_PUT/OP_RM or a mergeable code), ``reads`` are
        keys to fetch at the serialization point. Lock conflicts abort
        immediately (reason ``conflict``). Mergeable-only write sets
        take the fast path; otherwise the txn joins the 2PC lane."""
        topo = getattr(self.cluster, "topology", None)
        if topo is not None:
            # freeze gate (OUTSIDE the coordinator lock — it blocks):
            # keys in a migrating range queue here until the cutover
            # unfreezes them, so no txn admits against a mapping that
            # is about to flip. The router-version stamp below is the
            # backstop for the freeze starting after this gate passes.
            for _op, key, _val in writes:
                topo.gate_key(key)
            for key in reads:
                topo.gate_key(key)
        by_group: Dict[int, list] = {}
        for op, key, val in writes:
            by_group.setdefault(self.kvs.group_of(key), []).append(
                (op, key, val))
        fast = _merge.mergeable_plan(writes)
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            txn = Txn(tid, by_group, reads,
                      self.cluster.step_index + self.timeout_steps,
                      fast)
            tr = self._tracer()
            if tr is not None:
                txn.trace_id = tr.begin("txn", txn=tid,
                                        groups=list(txn.groups),
                                        fast=bool(fast))
            locked: List[Tuple[int, bytes]] = []
            ok = True
            for g, ws in by_group.items():
                for _op, key, _val in ws:
                    locked.append((g, key))
            for key in reads:
                locked.append((self.kvs.group_of(key), key))
            for lk in locked:
                if self._locks.get(lk, tid) != tid:
                    ok = False
                    break
                self._locks[lk] = tid
            if not ok:
                for lk in locked:
                    if self._locks.get(lk) == tid:
                        del self._locks[lk]
                txn.state = ABORTED
                txn.reason = "conflict"
                self._count_abort("conflict")
                if tr is not None and txn.trace_id is not None:
                    tr.end(txn.trace_id, status="aborted",
                           reason="conflict")
                return txn
            txn.router_version = getattr(self.kvs.router, "version", 0)
            txn.admitted = locked
            self._txns[tid] = txn
            if fast:
                if tr is not None and txn.trace_id is not None:
                    tr.phase(txn.trace_id, "merge")
                self._submit_merge(txn)
            elif self._active_2pc is None:
                self._active_2pc = tid
                self._submit_prepares(txn)
            else:
                if tr is not None and txn.trace_id is not None:
                    # queued behind the commit lane: the interval up
                    # to promotion's "prepare" phase is the blame
                    # report's txn_lock component
                    tr.phase(txn.trace_id, "lock_wait")
                self._queue.append(tid)
        return txn

    # ---------------- record submission ----------------

    def _conn(self, g: int, req: int) -> int:
        """PER-RECORD conn id: ``(client_id + req)`` pushed through the
        shared ShardedKVS group-namespacing. Client sessions dedup via
        the per-conn HIGH-WATER registry, which assumes FIFO per conn —
        the coordinator cannot promise that (records of concurrent
        transactions commit out of order across failover), so txn
        records dedup PER TID inside ``_fold_txn`` instead and never
        touch ``last_req``; the unique ``(conn, req)`` stamp remains
        the key the stamp loop (``note_appends``), spans, and the
        serializability checker's stream dedup all match records by.
        ``client_id`` (1<<20 by default) keeps the range far above real
        clients; ``req`` is unique per group so the mapping stays
        injective."""
        return self.kvs.conn_for(self.client_id + req, g)

    # holds-lock: _lock
    def _submit_record(self, txn: Txn, g: int, payload: bytes,
                      track: bool = False) -> int:
        """Submit one stamped record to ``g``'s current leader; spans
        ride the same (conn, req) key the stamp loop correlates."""
        self._req[g] += 1
        req = self._req[g]
        self._outstanding[(g, req)] = txn.tid
        if track:
            txn.record_index[(g, req)] = -1
            txn.record_payload[(g, req)] = payload
            txn.record_retry[(g, req)] = self.cluster.step_index
        lead = self.cluster.leader_hint(g)
        lead = lead if lead >= 0 else 0
        from rdma_paxos_tpu.obs.spans import active_recorder
        spans = active_recorder(getattr(self.cluster, "obs", None))
        if spans is not None:
            spans.begin(self._conn(g, req), req,
                        self.cluster._span_rep(g, lead),
                        phase="submit")
            txn.span_keys.add((g, req))
            tr = self._tracer()
            if tr is not None and txn.trace_id is not None:
                # child link: the record's span key joins it to the
                # txn-level trace on the merged timeline
                tr.link(txn.trace_id, self._conn(g, req), req, g)
        self.cluster.submit(g, lead, payload, conn=self._conn(g, req),
                            req_id=req)
        return req

    # holds-lock: _lock
    def _submit_prepares(self, txn: Txn) -> None:
        tr = self._tracer()
        if tr is not None and txn.trace_id is not None:
            tr.phase(txn.trace_id, "prepare")
        for g in txn.groups:
            txn.prep_appended[g] = 0
            for op, key, val in txn.writes_by_group[g]:
                req = self._submit_record(
                    txn, g, _records.encode_prepare(txn.tid, op, key,
                                                    val))
                txn.prep_reqs.setdefault(g, []).append(req)
            self._terms.reset(g)        # set at first prepare append
        if tr is not None and txn.trace_id is not None:
            tr.phase(txn.trace_id, "vote_wait")

    # holds-lock: _lock
    def _submit_merge(self, txn: Txn) -> None:
        # MERGE records (not plain commands): the fold applies them
        # immediately — still coordination-free — but dedups them per
        # tid and retires the tid's memory when the ``len(ws)``-th
        # record lands, so retried merges stay exactly-once WITHOUT
        # leaving a permanent per-record conn entry in ``last_req``
        for g in txn.groups:
            ws = txn.writes_by_group[g]
            for op, key, val in ws:
                self._submit_record(
                    txn, g,
                    _records.encode_merge(txn.tid, len(ws), op, key,
                                          val),
                    track=True)

    # holds-lock: _lock
    def _submit_decision(self, txn: Txn, commit: bool) -> None:
        mask = txn.participant_mask()
        reason = {"conflict": _records.ABORT_CONFLICT,
                  "timeout": _records.ABORT_TIMEOUT,
                  "failover": _records.ABORT_FAILOVER,
                  "topology": _records.ABORT_TOPOLOGY}.get(
                      txn.reason or "", 0)
        for g in txn.groups:
            payload = (_records.encode_commit(txn.tid, mask) if commit
                       else _records.encode_abort(txn.tid, reason))
            self._submit_record(txn, g, payload, track=True)
            self.cluster.clear_txn_watch(g)

    # ---------------- cluster hooks ----------------

    def note_appends(self, g: int, r: int, take: Sequence[tuple],
                     term: int, end_abs: int) -> None:
        """Stamp-loop hook (cluster.finish, invoked AFTER the host
        lock is released — this method takes the coordinator lock,
        which client threads hold while submitting, so calling it
        under the host lock would deadlock ABBA): the accepted prefix
        ``take`` landed at absolute indices ``[end_abs - len(take),
        end_abs)`` on ``g``'s leader ``r`` — match the coordinator's
        stamped records to learn each one's ``(term, index)`` and arm
        the group watch when the last prepare of a group is placed."""
        with self._lock:
            if not self._outstanding:
                return
            base = end_abs - len(take)
            for i, (_et, c, req, _p) in enumerate(take):
                if c != self._conn(g, req):
                    continue
                tid = self._outstanding.get((g, req))
                if tid is None:
                    continue
                txn = self._txns.get(tid)
                if txn is None:
                    continue
                index = base + i
                if (g, req) in txn.record_index:
                    # decision/merge record placed: completion is its
                    # index entering the group's commit frontier
                    # while the append term still rules
                    txn.record_index[(g, req)] = index
                    txn.record_term[(g, req)] = term
                    del self._outstanding[(g, req)]
                elif txn.state == PREPARING:
                    txn.prep_appended[g] += 1
                    self._terms.note(g, term)
                    del self._outstanding[(g, req)]
                    if (txn.prep_appended[g]
                            == len(txn.writes_by_group[g])):
                        # last prepare of g placed: watch it — votes
                        # ride the NEXT dispatch, but this dispatch's
                        # own readback may already prove the commit
                        # (observe's same-finish resolution)
                        txn.watch[g] = (index, term)
                        txn.watch_fresh[g] = True
                        self.cluster.set_txn_watch(g, index, term)

    def observe(self, cluster, res) -> None:
        """finish()-tail hook: consume the vote matrix, detect
        participant deposition, advance step-domain timeouts, and
        complete decided transactions whose records committed."""
        with self._lock:
            if not self._txns:
                return
            commit_abs = _epoch.commit_frontier(
                res, self.cluster.rebased_total)
            votes = res.get("txn_vote")
            rv = getattr(self.kvs.router, "version", 0)
            for txn in list(self._txns.values()):
                if (txn.state == PREPARING
                        and rv != txn.router_version
                        and any(self.kvs.group_of(k) != g
                                for g, k in txn.admitted)):
                    # an elastic cutover moved a participant key's
                    # group mid-flight: its staged prepares sit in a
                    # group the new routing never serves — abort
                    # deterministically (backstop; the freeze gate and
                    # the cutover's wants_serial() give-way make this
                    # rare)
                    self._abort(txn, "topology")
                if txn.state == PREPARING:
                    self._observe_preparing(txn, res, votes,
                                            commit_abs)
                if txn.state in (COMMITTING, ABORTING, MERGING):
                    self._observe_decided(txn, res, commit_abs)
                if (not txn.done and txn.state != COMMITTING
                        and cluster.step_index > txn.deadline):
                    # commit decisions are durable once made — only
                    # undecided (or merging/aborting) txns time out,
                    # and a merge past deadline keeps retrying via
                    # resubmission (its writes are already decided)
                    if txn.state in (PREPARING,):
                        self._abort(txn, "timeout")

    # holds-lock: _lock
    def _observe_preparing(self, txn: Txn, res, votes,
                           commit_abs) -> None:
        # deposition: a participant's leader advanced past the term
        # its prepares were appended under — the prepare may be
        # overwritten; abort deterministically (the vote lane's
        # CONFLICT is the committed-overwrite backstop)
        term_now = _epoch.term_now(res)
        for g in txn.prep_appended:
            if g in txn.prepared:
                # PREPARED is a quorum fact (committed under the
                # watched term) — a later term change cannot revoke
                # it, so a failover here must not abort the txn
                continue
            if self._terms.deposed(g, term_now[g]):
                self._abort(txn, "failover")
                return
        for g, (idx, wterm) in list(txn.watch.items()):
            if g in txn.prepared:
                continue
            if txn.watch_fresh.pop(g, False):
                # same-finish resolution: the prepare landed in THIS
                # dispatch under ``wterm``; if this finish's commit
                # frontier already covers it and the term is
                # unchanged, nothing can have overwritten it — the
                # common case resolves without waiting a dispatch for
                # the vote lane (⟹ cross-group commit ≈ 2 dispatches)
                if (_epoch.placement_status(idx, wterm, commit_abs[g],
                                            term_now[g])
                        == _epoch.COMPLETE):
                    txn.prepared.add(g)
                    self._close_prep_spans(txn, g)
                    self.cluster.clear_txn_watch(g)
                    continue
            if votes is None:
                continue
            row = votes[g]
            if (row == TXN_CONFLICT).any():
                self._abort(txn, "conflict")
                return
            if (row == TXN_PREPARED).any():
                txn.prepared.add(g)
                self._close_prep_spans(txn, g)
                self.cluster.clear_txn_watch(g)
        if txn.prepared == set(txn.groups):
            # serialization point: all participants hold the staged
            # writes durably — fetch the read set under the locks
            # through the LINEARIZABLE serving gate (lease/read-index
            # + apply-frontier), so captured reads cannot miss writes
            # committed by non-transactional clients. If a read key's
            # group cannot serve linearizably this step, retry next
            # observe — the step-domain deadline is the backstop.
            reads = {}
            for key in txn.read_keys:
                served, val = self._read_serialization_point(key)
                if not served:
                    return
                reads[key] = val
            txn.reads = reads
            txn.state = COMMITTING
            tr = self._tracer()
            if tr is not None and txn.trace_id is not None:
                tr.phase(txn.trace_id, "decide")
            self._submit_decision(txn, commit=True)

    # holds-lock: _lock
    def _read_serialization_point(self, key) -> Tuple[bool, Optional[bytes]]:
        """One read-set fetch at the serialization point: ``(served,
        value)``. The gate check (``serving_path``) then the bare
        table read (``serve_local``) is the ReadHub's linearization
        recipe — unlike ``kvs.get``, a ``None`` value here is
        unambiguously 'key absent', never 'gate refused'."""
        g = self.kvs.group_of(key)
        lm = getattr(self.cluster, "leases", None)
        r = lm.serving_holder(g) if lm is not None else -1
        if r < 0:
            r = self.cluster.leader_hint(g)
        if r < 0:
            return False, None
        kv = self.kvs.groups[g]
        if kv.serving_path(r) not in ("lease", "read_index"):
            return False, None
        return True, kv.serve_local(r, key)

    # retry patience before a decided record not yet appended is
    # resubmitted (shared epoch constant — topology seeding uses the
    # same patience for ITS stamped records)
    RETRY_STEPS = _epoch.RETRY_STEPS

    # holds-lock: _lock
    def _observe_decided(self, txn: Txn, res, commit_abs) -> None:
        term_now = _epoch.term_now(res)
        for (g, req), idx in list(txn.record_index.items()):
            st = _epoch.placement_status(
                idx, txn.record_term.get((g, req), 0), commit_abs[g],
                term_now[g])
            if st == _epoch.COMPLETE:
                del txn.record_index[(g, req)]
                txn.record_term.pop((g, req), None)
                txn.record_payload.pop((g, req), None)
                txn.record_retry.pop((g, req), None)
                self._close_record_spans(txn, [(g, req)], ok=True)
            elif st == _epoch.INVALIDATED:
                # forget the placement and retry under the SAME stamp:
                # if it DID commit, dedup makes the retry a no-op
                txn.record_index[(g, req)] = -1
                txn.record_retry[(g, req)] = self.cluster.step_index
            elif idx < 0:
                lead = self.cluster.leader_hint(g)
                if (lead >= 0 and self.cluster.step_index
                        > txn.record_retry[(g, req)] + self.RETRY_STEPS):
                    payload = txn.record_payload[(g, req)]
                    self._outstanding[(g, req)] = txn.tid
                    txn.record_retry[(g, req)] = self.cluster.step_index
                    self.cluster.submit(g, lead, payload,
                                        conn=self._conn(g, req),
                                        req_id=req)
        if not txn.record_index:
            self._finalize(txn)

    # ---------------- decisions ----------------

    # holds-lock: _lock
    def _abort(self, txn: Txn, reason: str) -> None:
        txn.reason = reason
        txn.state = ABORTING
        self._count_abort(reason)
        tr = self._tracer()
        if tr is not None and txn.trace_id is not None:
            tr.phase(txn.trace_id, "abort")
            tr.annotate(txn.trace_id, reason=reason)
            if reason == "topology":
                # blame the transition window: re-parent the txn trace
                # under the topology trace whose freeze made the
                # mapping move. Lock-free pointer read — taking the
                # controller's _lock here would invert drive()'s
                # topo-then-txn lock order (ABBA).
                topo = getattr(self.cluster, "topology", None)
                win = (getattr(topo, "window_trace", None)
                       or getattr(topo, "last_window_trace", None))
                if win is not None:
                    tr.set_parent(txn.trace_id, win)
        # close every span this txn still holds open — the abort
        # reason rides on the span so a mid-prepare abort never leaks
        # an open span (satellite: coordinator span-gap fix)
        self._close_record_spans(txn, list(txn.span_keys), ok=False,
                                 status="aborted:" + reason)
        # drop any still-outstanding prepare stamps
        for key, tid in list(self._outstanding.items()):
            if tid == txn.tid and key not in txn.record_index:
                del self._outstanding[key]
        for g in list(txn.watch):
            self.cluster.clear_txn_watch(g)
        txn.watch.clear()
        if txn.prep_appended:
            self._submit_decision(txn, commit=False)

    # holds-lock: _lock
    def _finalize(self, txn: Txn) -> None:
        if txn.state == COMMITTING:
            txn.state = COMMITTED
            self.committed_total += 1
            obs = getattr(self.cluster, "obs", None)
            if obs is not None:
                obs.metrics.inc("txn_committed_total")
        elif txn.state == ABORTING:
            txn.state = ABORTED
        elif txn.state == MERGING:
            # fast path: every merge command committed — convergent by
            # commutativity, atomic in the no-torn-intermediate sense
            txn.state = COMMITTED
            self.committed_total += 1
            obs = getattr(self.cluster, "obs", None)
            if obs is not None:
                obs.metrics.inc("txn_committed_total")
        # safety net: any span key still open (decision records of an
        # aborted txn, crash-interrupted prepares) closes here, then
        # the txn-level trace ends with the terminal state
        ok = txn.state == COMMITTED
        self._close_record_spans(
            txn, list(txn.span_keys), ok=ok,
            status="aborted:" + (txn.reason or "unknown"))
        tr = self._tracer()
        if tr is not None and txn.trace_id is not None:
            tr.end(txn.trace_id,
                   status=("committed" if ok else "aborted"))
        self._release(txn)

    # holds-lock: _lock
    def _count_abort(self, reason: str) -> None:
        self.aborted_total[reason] += 1
        obs = getattr(self.cluster, "obs", None)
        if obs is not None:
            obs.metrics.inc("txn_aborted_total", reason=reason)

    # holds-lock: _lock
    def _release(self, txn: Txn) -> None:
        for lk, tid in list(self._locks.items()):
            if tid == txn.tid:
                del self._locks[lk]
        self._txns.pop(txn.tid, None)
        for key, tid in list(self._outstanding.items()):
            if tid == txn.tid:
                del self._outstanding[key]
        if self._active_2pc == txn.tid:
            self._active_2pc = None
            while self._queue:
                nxt = self._txns.get(self._queue.popleft())
                if nxt is not None and not nxt.done:
                    self._active_2pc = nxt.tid
                    # the timeout budget covers the 2PC rounds, not
                    # the FIFO wait — restart it at promotion or a
                    # queued txn aborts 'timeout' the moment (or soon
                    # after) its prepares finally go out
                    nxt.deadline = (self.cluster.step_index
                                    + self.timeout_steps)
                    self._submit_prepares(nxt)
                    break

    # ---------------- driver surface ----------------

    def wants_serial(self) -> bool:
        """True while any transaction is in flight: the commit lane
        (votes, decision records) rides SERIAL dispatches only, so the
        drivers hold bursts/pipelining — the same give-way rule
        elections and repair already follow."""
        with self._lock:
            return bool(self._txns)

    def health(self) -> dict:
        with self._lock:
            return dict(
                active=len(self._txns),
                queued=len(self._queue),
                locks=len(self._locks),
                committed_total=self.committed_total,
                aborted_total=dict(self.aborted_total))


def attach_coordinator(kvs, *, client_id: int = 1 << 20,
                       timeout_steps: int = 64) -> TxnCoordinator:
    """Build a coordinator over ``kvs`` (a ShardedKVS on a txn=True
    cluster) and attach it at ``cluster.txn`` — the finish() tail and
    stamp loop start feeding it, and the drivers' give-way gates see
    it through the same attach point."""
    coord = TxnCoordinator(kvs, client_id=client_id,
                           timeout_steps=timeout_steps)
    kvs.shard.txn = coord
    return coord
