"""Cross-group atomic transactions over the sharded consensus engine.

Layout mirrors the device/host split the analysis passes enforce:

* :mod:`rdma_paxos_tpu.txn.lane` — device-pure vote constants and the
  prepare-vote rule compiled into the ``txn=`` step variant (the only
  module ``consensus/step.py`` imports from this package).
* :mod:`rdma_paxos_tpu.txn.coordinator` — the host 2PC state machine
  (begin/prepare/commit/abort, step-domain timeouts, participant
  locks, abort on leader failover).
* :mod:`rdma_paxos_tpu.txn.api` — ``transact()``, the client surface
  ``ShardedKVS`` exposes.
* :mod:`rdma_paxos_tpu.txn.merge` — the mergeable-op fast path
  (INCR / add-to-set / max-register commit as independent per-group
  entries, no prepare).
* :mod:`rdma_paxos_tpu.txn.chaos` — the seeded coordinator-crash
  nemesis runner behind the CI strict-serializability smoke.

Host symbols resolve lazily so importing the package (e.g. via the
device lane from inside jit tracing) never pulls host modules.
"""

from __future__ import annotations

import importlib

_LAZY = {
    "TXN_NONE": "lane", "TXN_PENDING": "lane",
    "TXN_PREPARED": "lane", "TXN_CONFLICT": "lane",
    "prepare_vote": "lane",
    "Txn": "coordinator", "TxnCoordinator": "coordinator",
    "attach_coordinator": "coordinator",
    "TxnHandle": "api", "transact": "api",
    "MERGE_FNS": "merge", "is_mergeable": "merge",
    "mergeable_plan": "merge",
    "TxnNemesisRunner": "chaos", "run_txn_chaos": "chaos",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
