"""rdma_paxos_tpu — a TPU-native replicated-state-machine framework.

A ground-up rebuild of the capabilities of APUS / RDMA-PAXOS
(wnagchenghku/RDMA-PAXOS): transparent state-machine replication of
unmodified TCP server applications via LD_PRELOAD interposition, backed by a
DARE-style strong-leader consensus core — except the log-replication hot loop
runs as JAX collectives over TPU ICI (one replica per chip) instead of
one-sided RDMA verbs.

Architecture (TPU-first, not a port — see SURVEY.md §7):

- ``consensus/``  — the replicated log (fixed-shape on-device ring buffer)
  and the SPMD replica step: batched append, leader fan-out (masked-psum
  broadcast — the analog of the one-sided RDMA WRITE of
  ``rc_write_remote_logs``, reference ``src/dare/dare_ibv_rc.c:1870``),
  term-gated accept + divergence truncation (the analog of
  ``log_adjustment``, ``dare_ibv_rc.c:1292``), ACK gather, majority-quorum
  commit, one-round leader election, heartbeats — all one jitted collective
  program over a ``replica`` mesh axis.
- ``ops/``        — Pallas TPU kernels for the hot scans (quorum/commit).
- ``parallel/``   — mesh construction, shard_map wrapper, and a
  ``vmap(axis_name=...)`` emulation path so the identical protocol code runs
  N replicas on a single chip or one replica per chip on a slice.
- ``runtime/``    — host control plane: per-replica driver loop (the libev
  ``polling()`` analog, reference ``src/dare/dare_server.c:1004``), timers
  with adaptive election timeout (``to_adjust_cb``, ``dare_server.c:763``),
  membership/bootstrap over TCP (the UD/multicast analog,
  ``src/dare/dare_ibv_ud.c``), snapshot recovery.
- ``proxy/``      — RSM client / replay engine (reference
  ``src/proxy/proxy.c``): connection-id map, event queue → device batch
  marshalling, follower loopback-TCP replay, stable store.
- ``models/``     — built-in replicated state machines (device-native KVS,
  the ``dare_kvs_sm`` analog, reference ``src/dare/dare_kvs_sm.c``).
- ``native/``     — C++ runtime pieces: the LD_PRELOAD interposition shim
  (reference ``src/spec_hooks.cpp``) and the append-only stable store
  (reference ``src/db/db-interface.c``), bound via ctypes.
"""

__version__ = "0.1.0"

from rdma_paxos_tpu.config import (  # noqa: F401
    LogConfig,
    TimeoutConfig,
    ClusterConfig,
)
