"""Device-native replicated KVS — the built-in state machine.

Reference: ``dare_kvs_sm.c`` implements a chained-hash KVS as the abstract
state machine (``dare_sm_t`` vtable, ``dare_sm.h:49-60``) with PUT/GET/RM
(``apply_kvs_cmd`` ``:158-202``). In APUS mode the proxy replaces it; in
standalone-DARE mode it IS the replicated service and the snapshot unit.

TPU-native redesign: a fixed-capacity **open-addressing** hash table held in
JAX arrays (SoA), applied with vectorized probe sequences — no chains, no
pointers, no dynamic allocation:

* ``keys  [cap, KEY_W] i32`` — zero-padded key words
* ``vals  [cap, VAL_W] i32``
* ``used  [cap] i32``       — slot occupancy (1 = live)

A lookup hashes the key words (FNV-style mix) and gathers ``PROBES``
quadratic-probe slots at once; PUT picks the match-or-first-free slot, RM
tombstones in place (occupancy only — probe chains stay intact because
probing always scans all ``PROBES`` candidates). Commands arrive as log
entries (type CSM in the reference; here the KVS consumes SEND-entry
payloads) and a committed batch applies under ``lax.scan`` — so in
standalone mode the whole service is jit-compiled end to end.

Command encoding (int32 words): ``[op, key[KEY_W], val[VAL_W]]``,
op ∈ {1=PUT, 2=GET, 3=RM, 4=INCR, 5=SADD, 6=MAX}.

Ops 4-6 are the MERGEABLE family (the txn/ fast path, after SafarDB's
replicated-data-type commits): each is a commutative, associative fold
of the operand into the current value — elementwise i32 add (INCR),
bitwise-OR set union over the 256 value bits (SADD), elementwise max
(MAX) — so concurrent merges to one key converge regardless of log
interleaving and a cross-group transaction of only-mergeable writes
commits as independent per-group entries with NO prepare phase.
An absent key folds against zeros (the family's identity).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

OP_PUT, OP_GET, OP_RM = 1, 2, 3
OP_INCR, OP_SADD, OP_MAX = 4, 5, 6
KEY_W, VAL_W = 8, 8
CMD_W = 1 + KEY_W + VAL_W
PROBES = 32   # probe depth bounds the usable load factor (~0.5 is safe)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVState:
    keys: jax.Array   # [cap, KEY_W] i32
    vals: jax.Array   # [cap, VAL_W] i32
    used: jax.Array   # [cap] i32

    @property
    def cap(self) -> int:
        return self.keys.shape[0]


def make_kvs(cap: int = 4096) -> KVState:
    if cap & (cap - 1):
        raise ValueError("cap must be a power of two")
    return KVState(
        keys=jnp.zeros((cap, KEY_W), jnp.int32),
        vals=jnp.zeros((cap, VAL_W), jnp.int32),
        used=jnp.zeros((cap,), jnp.int32),
    )


def _hash(key: jax.Array) -> jax.Array:
    """FNV-ish mix of the key words to a 31-bit bucket seed."""
    h = jnp.uint32(2166136261)
    for i in range(KEY_W):
        h = (h ^ key[i].astype(jnp.uint32)) * jnp.uint32(16777619)
    return (h & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)


def _probe_slots(key: jax.Array, cap: int) -> jax.Array:
    """Quadratic probe sequence, PROBES candidates."""
    h = _hash(key)
    i = jnp.arange(PROBES, dtype=jnp.int32)
    return jnp.bitwise_and(h + i * (i + 1) // 2, cap - 1)


def _find(kv: KVState, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Returns (slot_of_match_or_-1, first_free_slot_or_-1)."""
    slots = _probe_slots(key, kv.cap)                   # [P]
    cand_keys = kv.keys[slots]                          # [P, KEY_W]
    occupied = kv.used[slots] > 0                       # [P]
    match = occupied & jnp.all(cand_keys == key[None, :], axis=1)
    free = ~occupied
    P = PROBES
    midx = jnp.min(jnp.where(match, jnp.arange(P), P))
    fidx = jnp.min(jnp.where(free, jnp.arange(P), P))
    mslot = jnp.where(midx < P, slots[jnp.minimum(midx, P - 1)], -1)
    fslot = jnp.where(fidx < P, slots[jnp.minimum(fidx, P - 1)], -1)
    return mslot, fslot


def apply_cmd(kv: KVState, cmd: jax.Array) -> Tuple[KVState, jax.Array]:
    """Apply one encoded command word-row; returns (kv', value_or_zeros).

    GET returns the value words (zeros if absent); PUT/RM return zeros.
    Unknown ops are no-ops — a committed garbage entry must not wedge the
    state machine (apply_kvs_cmd tolerates the same way)."""
    op = cmd[0]
    key = cmd[1:1 + KEY_W]
    val = cmd[1 + KEY_W:1 + KEY_W + VAL_W]
    mslot, fslot = _find(kv, key)

    target = jnp.where(mslot >= 0, mslot, fslot)
    # mergeable family: fold the operand into the CURRENT value — read
    # through mslot only (an RM tombstone leaves stale words at free
    # slots, so the absent-key identity must be zeros, never vals[t])
    m0 = jnp.maximum(mslot, 0)
    base = jnp.where(mslot >= 0, kv.vals[m0],
                     jnp.zeros((VAL_W,), jnp.int32))
    is_merge = (op == OP_INCR) | (op == OP_SADD) | (op == OP_MAX)
    merged = jnp.where(
        op == OP_INCR, base + val,
        jnp.where(op == OP_SADD, base | val, jnp.maximum(base, val)))
    do_put = ((op == OP_PUT) | is_merge) & (target >= 0)
    wval = jnp.where(is_merge, merged, val)
    t = jnp.maximum(target, 0)
    keys = kv.keys.at[t].set(jnp.where(do_put, key, kv.keys[t]))
    vals = kv.vals.at[t].set(jnp.where(do_put, wval, kv.vals[t]))
    used = kv.used.at[t].set(jnp.where(do_put, 1, kv.used[t]))

    do_rm = (op == OP_RM) & (mslot >= 0)
    m = jnp.maximum(mslot, 0)
    used = used.at[m].set(jnp.where(do_rm, 0, used[m]))

    hit = (op == OP_GET) & (mslot >= 0)
    out = jnp.where(hit, kv.vals[m], jnp.zeros((VAL_W,), jnp.int32))
    return KVState(keys, vals, used), out


def apply_batch(kv: KVState, cmds: jax.Array,
                count: jax.Array) -> Tuple[KVState, jax.Array]:
    """Apply ``count`` commands from ``cmds [B, CMD_W]`` in log order via
    ``lax.scan`` (the committed-window apply of standalone mode)."""
    B = cmds.shape[0]

    def one(kv, xs):
        cmd, idx = xs
        nkv, out = apply_cmd(kv, cmd)
        skip = idx >= count
        nkv = jax.tree.map(lambda a, b: jnp.where(skip, a, b), kv, nkv)
        return nkv, jnp.where(skip, 0, out)

    return jax.lax.scan(one, kv, (cmds, jnp.arange(B, dtype=jnp.int32)))


# ---------------------------------------------------------------------------
# host-side encoding helpers
# ---------------------------------------------------------------------------

def encode_cmd(op: int, key: bytes, val: bytes = b"") -> np.ndarray:
    if len(key) > KEY_W * 4 or len(val) > VAL_W * 4:
        raise ValueError("key/value too large")
    k = np.zeros(KEY_W * 4, np.uint8)
    v = np.zeros(VAL_W * 4, np.uint8)
    k[:len(key)] = np.frombuffer(key, np.uint8)
    v[:len(val)] = np.frombuffer(val, np.uint8)
    return np.concatenate([
        np.array([op], "<i4"),
        k.view("<i4"), v.view("<i4")]).astype("<i4")


def decode_val(words: np.ndarray) -> bytes:
    return words.astype("<i4").tobytes().rstrip(b"\x00")
