"""Standalone-DARE mode: the device KVS served directly over consensus.

In the reference, standalone DARE (no app interposition) replicates KVS
commands as CSM log entries and applies them through the ``dare_sm_t``
vtable (``dare_server.c:269``, ``dare_kvs_sm.c``); clients read via the
leader after a leadership verification (``ep_dp_reply_read_req``,
``dare_ep_db.c:132-161``).

Here: PUT/RM commands ride SEND entries through the same replicated log;
every replica folds its committed stream into its own device-resident
:mod:`rdma_paxos_tpu.models.kvs` table; linearizable GETs are served from
the leader's table only when the latest step verified leadership
(read-index). Weak (possibly stale) GETs can be served by any replica —
the same trade the reference's follower apps offer.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.models.kvs import (
    CMD_W, OP_GET, OP_PUT, OP_RM, KVState, apply_cmd, decode_val,
    encode_cmd, make_kvs)
from rdma_paxos_tpu.runtime.sim import SimCluster


class ReplicatedKVS:
    """KVS service over a :class:`SimCluster` (or a driver's cluster)."""

    def __init__(self, cluster: SimCluster, cap: int = 4096):
        self.c = cluster
        self.tables: List[KVState] = [make_kvs(cap)
                                      for _ in range(cluster.R)]
        self._cursor = [0] * cluster.R
        self._apply_jit = jax.jit(apply_cmd)

    # ------------------------------------------------------------------

    def _fold(self, r: int) -> None:
        """Fold newly committed commands into replica r's table."""
        stream = self.c.replayed[r]
        while self._cursor[r] < len(stream):
            etype, _conn, _req, payload = stream[self._cursor[r]]
            self._cursor[r] += 1
            if etype != int(EntryType.SEND):
                continue
            if len(payload) != CMD_W * 4:
                continue                      # not a KVS command: skip
            cmd = jnp.asarray(np.frombuffer(payload, "<i4"))
            self.tables[r], _ = self._apply_jit(self.tables[r], cmd)

    # ------------------------------------------------------------------

    def put(self, leader: int, key: bytes, val: bytes) -> None:
        self.c.submit(leader, encode_cmd(OP_PUT, key, val).tobytes())

    def remove(self, leader: int, key: bytes) -> None:
        self.c.submit(leader, encode_cmd(OP_RM, key).tobytes())

    def get(self, r: int, key: bytes, *,
            linearizable: bool = False) -> Optional[bytes]:
        """Read from replica ``r``'s table. With ``linearizable=True`` the
        read is refused (returns None) unless ``r`` verified leadership on
        the latest step — the read-index rule."""
        if linearizable:
            last = self.c.last
            if last is None or not last["leadership_verified"][r]:
                return None
        self._fold(r)
        _, out = self._apply_jit(self.tables[r],
                                 jnp.asarray(encode_cmd(OP_GET, key)))
        v = decode_val(np.asarray(out))
        return v if v else None
