"""Standalone-DARE mode: the device KVS served directly over consensus.

In the reference, standalone DARE (no app interposition) replicates KVS
commands as CSM log entries and applies them through the ``dare_sm_t``
vtable (``dare_server.c:269``, ``dare_kvs_sm.c``); clients read via the
leader after a leadership verification (``ep_dp_reply_read_req``,
``dare_ep_db.c:132-161``).

Here: PUT/RM commands ride SEND entries through the same replicated log;
every replica folds its committed stream into its own device-resident
:mod:`rdma_paxos_tpu.models.kvs` table; linearizable GETs are served from
the leader's table only when the latest step verified leadership
(read-index). Weak (possibly stale) GETs can be served by any replica —
the same trade the reference's follower apps offer.
"""

from __future__ import annotations

import collections
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.models.kvs import (
    CMD_W, OP_GET, OP_PUT, OP_RM, KVState, apply_cmd, decode_val,
    encode_cmd, make_kvs)
from rdma_paxos_tpu.txn.records import (
    TXN_ABORT, TXN_CMD_W, TXN_COMMIT, TXN_MERGE, TXN_PREPARE)
from rdma_paxos_tpu.runtime.sim import SimCluster

# capacity of the per-replica ring of finished (decided/complete)
# transaction ids: duplicate txn records (decisions and merges are
# retried under their ORIGINAL stamp across failover) trail their
# first committed copy by at most the retry patience plus a couple of
# confirmation dispatches, all of them SERIAL while the transaction
# is live (the coordinator's wants_serial gate), so the stream gap
# between a record and its last duplicate is a few hundred entries —
# orders of magnitude under this bound
TXN_DONE_CAP = 65536


class ReplicatedKVS:
    """KVS service over a :class:`SimCluster` (or a driver's cluster).

    ``cluster`` is duck-typed: any engine exposing the SimCluster
    client surface (``R``, ``submit``, ``replayed``, ``last``,
    ``obs``) works — the sharded layer reuses this class per group
    through exactly such a facade
    (:class:`rdma_paxos_tpu.shard.kvs._GroupFacade`), so sharding adds
    routing without forking the state-machine fold."""

    def __init__(self, cluster: SimCluster, cap: int = 4096):
        self.c = cluster
        # consensus group this instance serves (set by ShardedKVS);
        # labels the dedup metric series so per-group dedup pressure
        # is observable — None = unsharded, unlabeled legacy series
        self.group: Optional[int] = None
        self.tables: List[KVState] = [make_kvs(cap)
                                      for _ in range(cluster.R)]
        self._cursor = [0] * cluster.R
        self._apply_jit = jax.jit(apply_cmd)
        self._get_many_jit = None      # compiled lazily on first batch
        self._get_cmds: dict = {}      # GET-encoding cache (hot keys)
        # per-replica endpoint registry: client_id -> highest applied
        # req_id (the dare_ep_db ``last_req_id`` analog,
        # dare_ep_db.h:20-30). Folded DETERMINISTICALLY from the
        # committed stream, so every replica — including any future
        # leader — skips retransmitted requests identically; dedup
        # therefore survives reconnects and failover
        # (dare_ibv_ud.c:1004-1014 dedups the same way at the leader).
        self.last_req: List[dict] = [dict() for _ in range(cluster.R)]
        self.deduped: List[int] = [0] * cluster.R
        # optional chaos.history.HistoryRecorder: when attached, every
        # client-visible operation (session PUT/RM, weak and read-index
        # GETs, retransmits) is recorded as invoke/ok/fail events for
        # the linearizability checker. Host-side bookkeeping only.
        self.history = None
        # txn staging + exactly-once (txn/records.py): per-replica
        # tid -> {"reqs": stamped reqs folded so far, "staged":
        # buffered kvs-command words}, folded DETERMINISTICALLY from
        # the committed stream like last_req — a PREPARE record stages
        # its embedded write, the COMMIT record applies the buffer in
        # staging order, ABORT drops it, MERGE applies immediately.
        # Writes of an aborted (or never-decided) transaction never
        # reach the table. Dedup is PER TID (every coordinator record
        # is uniquely stamped), so the registry holds only live tids:
        # a finished tid moves to the bounded done-ring below and its
        # entry here is dropped — where a per-conn high-water registry
        # would keep one entry per coordinator record forever.
        self._txn_buf: List[dict] = [dict() for _ in range(cluster.R)]
        self._txn_done: List[set] = [set() for _ in range(cluster.R)]
        self._txn_done_fifo: List[collections.deque] = [
            collections.deque() for _ in range(cluster.R)]
        self.txn_applied: List[int] = [0] * cluster.R
        self.txn_discarded: List[int] = [0] * cluster.R

    def _spans(self):
        """The cluster's span recorder when causal tracing is on —
        session mutations are span births keyed (client_id, req_id),
        the same stamp that rides the entry's M_CONN/M_REQID columns
        (so the sim's append hook correlates them with (term, index))."""
        from rdma_paxos_tpu.obs.spans import active_recorder
        return active_recorder(getattr(self.c, "obs", None))

    def _span_rep(self, r: int) -> int:
        """Span-track replica id for local replica ``r``: the cluster
        may namespace replica ids (the sharded engine uses ``g*R + r``
        so per-group tracks never collide) — every span event this
        layer records must use the SAME namespace the cluster's
        append/commit/apply stamps use."""
        f = getattr(self.c, "span_replica", None)
        return f(r) if f is not None else r

    # ------------------------------------------------------------------

    def rebuild(self, r: int) -> None:
        """Crash-restart of replica ``r``'s app process: discard the
        device table and dedup registry (volatile) and refold from the
        replayed stream (the StableStore analog — replay IS the
        driver's recovery path). The fold is deterministic, so the
        rebuilt table, registry, and dedup decisions match exactly what
        the pre-crash incarnation derived."""
        self.tables[r] = make_kvs(int(self.tables[r].cap))
        self._cursor[r] = 0
        self.last_req[r] = dict()
        self.deduped[r] = 0
        self._txn_buf[r] = dict()
        self._txn_done[r] = set()
        self._txn_done_fifo[r] = collections.deque()
        self.txn_applied[r] = 0
        self.txn_discarded[r] = 0

    # ------------------------------------------------------------------

    def _fold(self, r: int) -> None:
        """Fold newly committed commands into replica r's table."""
        stream = self.c.replayed[r]
        n = len(stream)
        if self._cursor[r] >= n:
            return
        if hasattr(stream, "segments_from"):
            # consume ReplayBatch segments WITHOUT materializing the
            # stream: indexing would flatten the batches to legacy
            # tuples and destroy the log coordinates the streams/
            # tail followers decode for resume tokens and CDC records
            rows = []
            for seg in stream.segments_from(self._cursor[r]):
                rows.extend(seg.tuples() if hasattr(seg, "tuples")
                            else seg)
        else:
            rows = [stream[i] for i in range(self._cursor[r], n)]
        self._cursor[r] = n
        for etype, conn, req, payload in rows:
            if etype != int(EntryType.SEND):
                continue
            if len(payload) == TXN_CMD_W * 4:
                # 2PC record — the distinct width keeps legacy folds
                # skipping it; the (conn, req) dedup rule below covers
                # it in _fold_txn, so a coordinator retransmit after
                # failover stages/decides exactly once
                self._fold_txn(r, conn, req, payload)
                continue
            if len(payload) != CMD_W * 4:
                continue                      # not a KVS command: skip
            if req > 0 and conn > 0:
                # session-stamped command: apply exactly once
                if req <= self.last_req[r].get(conn, 0):
                    self.deduped[r] += 1
                    obs = getattr(self.c, "obs", None)
                    if obs is not None:
                        if self.group is not None:
                            obs.metrics.inc("kvs_deduped_total",
                                            replica=r, group=self.group)
                        else:
                            obs.metrics.inc("kvs_deduped_total",
                                            replica=r)
                    continue
                self.last_req[r][conn] = req
            cmd = jnp.asarray(np.frombuffer(payload, "<i4"))
            self.tables[r], _ = self._apply_jit(self.tables[r], cmd)

    def _txn_retire(self, r: int, tid: int) -> None:
        """Move ``tid`` to replica ``r``'s done-ring: late duplicates
        (retried decisions/merges) and stragglers of a finished
        transaction are dropped without per-record registry residue."""
        done = self._txn_done[r]
        if tid in done:
            return
        done.add(tid)
        fifo = self._txn_done_fifo[r]
        fifo.append(tid)
        while len(fifo) > TXN_DONE_CAP:
            done.discard(fifo.popleft())

    def _fold_txn(self, r: int, conn: int, req: int,
                  payload: bytes) -> None:
        """Fold one committed txn record (txn/records.py layout):
        PREPARE stages its embedded write per tid, COMMIT applies the
        tid's staged writes in staging order, ABORT drops them, MERGE
        applies immediately (commutative — no staging needed) and
        retires the tid once its last merge record lands. Exactly-once
        is per tid: stamped duplicates dedup against the live tid's
        req set or the done-ring, NOT the session ``last_req``
        registry (single-record coordinator conns would grow it
        forever). A record for an already-finished tid — a retried
        duplicate, or a PREPARE landing after its transaction's
        decision — is dropped, so nothing can stage under a dead tid.
        Deterministic over the committed stream, so every replica —
        and any rebuild — derives the same table."""
        from rdma_paxos_tpu.txn.records import decode_record
        txn_op, tid, arg, cmd_words = decode_record(payload)
        if tid in self._txn_done[r]:
            self.deduped[r] += 1
            return
        stamped = req > 0 and conn > 0
        buf = self._txn_buf[r]
        if txn_op in (TXN_PREPARE, TXN_MERGE):
            ent = buf.setdefault(tid, {"reqs": set(), "staged": []})
            if stamped:
                if req in ent["reqs"]:
                    self.deduped[r] += 1
                    return
                ent["reqs"].add(req)
            if txn_op == TXN_PREPARE:
                ent["staged"].append(np.asarray(cmd_words))
                return
            self.tables[r], _ = self._apply_jit(
                self.tables[r], jnp.asarray(cmd_words))
            self.txn_applied[r] += 1
            if stamped and len(ent["reqs"]) == arg:
                # the coordinator submits exactly ``arg`` merge
                # records here — all folded, the tid is complete
                del buf[tid]
                self._txn_retire(r, tid)
        elif txn_op == TXN_COMMIT:
            ent = buf.pop(tid, None)
            for cmd in (ent["staged"] if ent else ()):
                self.tables[r], _ = self._apply_jit(
                    self.tables[r], jnp.asarray(cmd))
                self.txn_applied[r] += 1
            self._txn_retire(r, tid)
        elif txn_op == TXN_ABORT:
            ent = buf.pop(tid, None)
            self.txn_discarded[r] += (len(ent["staged"]) if ent
                                      else 0)
            self._txn_retire(r, tid)

    # ------------------------------------------------------------------

    def put(self, leader: int, key: bytes, val: bytes, *,
            client_id: int = 0, req_id: int = 0) -> None:
        self.c.submit(leader, encode_cmd(OP_PUT, key, val).tobytes(),
                      conn=client_id, req_id=req_id)

    def remove(self, leader: int, key: bytes, *,
               client_id: int = 0, req_id: int = 0) -> None:
        self.c.submit(leader, encode_cmd(OP_RM, key).tobytes(),
                      conn=client_id, req_id=req_id)

    def merge(self, leader: int, op: int, key: bytes, val: bytes, *,
              client_id: int = 0, req_id: int = 0) -> None:
        """Submit one mergeable write (OP_INCR/OP_SADD/OP_MAX) — a
        plain single-group command; the txn fast path rides these."""
        self.c.submit(leader, encode_cmd(op, key, val).tobytes(),
                      conn=client_id, req_id=req_id)

    def session(self, client_id: int) -> "ClientSession":
        """Open a retransmitting-client session (the UD-client analog)."""
        return ClientSession(self, client_id)

    def serving_path(self, r: int) -> str:
        """The linearizable serving gate as a standalone check:
        ``"lease"`` / ``"read_index"`` when replica ``r`` may serve a
        linearizable read NOW (see :meth:`get` for the two paths),
        ``"quarantined"`` / ``"refused"`` when it must not. Callers
        that establish the linearization point themselves (the
        ReadHub, the txn coordinator's serialization-point reads) pair
        this with :meth:`serve_local` — unlike :meth:`get`'s ``None``,
        the gate verdict is never ambiguous with a missing key."""
        # a quarantined/recovering replica must not serve at all —
        # not even through a stale leadership_verified snapshot
        # from the step before its links were cut (the repair
        # pipeline revokes its lease; this closes the one-step
        # read-index window too). read_blocked covers the repair
        # holds need_recovery does not: the storm policy leaves
        # replay running, and the digest path drops need_recovery
        # at install time while probation still bars serving.
        if (r in getattr(self.c, "need_recovery", ())
                or r in getattr(self.c, "read_blocked", ())):
            return "quarantined"
        lm = getattr(self.c, "leases", None)
        g = self.group if self.group is not None else 0
        last = self.c.last
        # the serving frontier gate the hub also enforces: the
        # local apply cursor must cover the replica's own commit
        # index, else state already ACKED to writers is missing
        # from the table (a wedged apply keeps acking windows, so
        # leadership_verified — and the lease — stay live while
        # applied freezes below commit)
        applied = getattr(self.c, "applied", None)
        caught_up = (last is not None and applied is not None
                     and int(applied[r])
                     >= int(last["commit"][r]))
        if caught_up and lm is not None and lm.valid(g, r):
            return "lease"
        if caught_up and last["leadership_verified"][r]:
            return "read_index"
        return "refused"

    def get(self, r: int, key: bytes, *,
            linearizable: bool = False) -> Optional[bytes]:
        """Read from replica ``r``'s table. A ``linearizable=True``
        read serves through one of two zero-log-traffic paths:

        * **lease** — ``r`` holds a valid step-domain leader lease
          (``cluster.leases`` attached via ``runtime/reads.py``): no
          per-read verification round at all, the renewal rides the
          heartbeat/quorum machinery the protocol already runs;
        * **read_index** — ``r`` verified leadership on the latest
          finished step (the pre-lease rule, and the fallback a new
          leader uses while it waits out the old lease).

        Refused (returns None, recorded as a FAIL — the read
        definitively did not happen) when neither holds."""
        t0 = time.monotonic() if linearizable else None
        op_id = (self.history.invoke("get", key, replica=r,
                                     weak=not linearizable)
                 if self.history is not None else None)
        path = None
        if linearizable:
            path = self.serving_path(r)
            if path == "quarantined":
                if op_id is not None:
                    self.history.fail(op_id, reason="quarantined")
                return None
            if path == "refused":
                # a REFUSED read definitively did not happen — fail,
                # not timeout (the checker drops it, constraint-free)
                if op_id is not None:
                    self.history.fail(op_id,
                                      reason="leadership_unverified")
                return None
        self._fold(r)
        _, out = self._apply_jit(self.tables[r],
                                 jnp.asarray(encode_cmd(OP_GET, key)))
        v = decode_val(np.asarray(out))
        v = v if v else None
        if path is not None:
            from rdma_paxos_tpu.runtime.reads import count_read
            count_read(getattr(self.c, "obs", None), path, r,
                       group=self.group, t0=t0)
        if op_id is not None:
            self.history.ok(op_id, v)
        return v

    def serve_local(self, r: int, key: bytes) -> Optional[bytes]:
        """Bare local table read (fold + lookup) with NO linearization
        gate and NO accounting — the serve callback for hub-queued
        reads, whose linearization point (lease validity or confirmed
        read index + apply frontier) the :class:`ReadHub` establishes
        before invoking it."""
        self._fold(r)
        _, out = self._apply_jit(self.tables[r],
                                 jnp.asarray(encode_cmd(OP_GET, key)))
        v = decode_val(np.asarray(out))
        return v if v else None

    # batched local GETs: one vmapped dispatch per power-of-two tier
    # instead of a per-key apply dispatch — how a leaseholder (or a
    # read-index follower) serves a read BURST cheaply
    _GET_TIERS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

    def get_many(self, r: int, keys) -> List[Optional[bytes]]:
        """Serve a batch of local reads from replica ``r``'s table in
        ONE vmapped device dispatch (padded to a power-of-two tier so
        compiles stay bounded). Linearization gating and accounting
        are the CALLER's job — this is the serving primitive the
        lease/read-index paths and the read-mix bench share."""
        if not keys:
            return []
        self._fold(r)
        if self._get_many_jit is None:
            self._get_many_jit = jax.jit(jax.vmap(
                lambda kv, cmd: apply_cmd(kv, cmd)[1],
                in_axes=(None, 0)))
        out: List[Optional[bytes]] = []
        i = 0
        while i < len(keys):
            chunk = keys[i:i + self._GET_TIERS[-1]]
            tier = next(t for t in self._GET_TIERS
                        if t >= len(chunk))
            cmds = np.zeros((tier, CMD_W), "<i4")
            for j, k in enumerate(chunk):
                # hot read sets repeat keys: cache their encodings
                row = self._get_cmds.get(k)
                if row is None:
                    row = encode_cmd(OP_GET, k)
                    if len(self._get_cmds) < 65536:
                        self._get_cmds[k] = row
                cmds[j] = row
            vals = np.asarray(self._get_many_jit(
                self.tables[r], jnp.asarray(cmds)))
            for j in range(len(chunk)):
                v = decode_val(vals[j])
                out.append(v if v else None)
            i += len(chunk)
        return out

    def items_in_range(self, r: int, lo: bytes,
                       hi: Optional[bytes]) -> List[Tuple[bytes, bytes]]:
        """Every live ``(key, value)`` pair in ``[lo, hi)`` (byte-
        lexicographic; ``hi=None`` = unbounded) from replica ``r``'s
        folded table, sorted by key — the topology transition's
        donor-side enumeration primitive (what must be seeded into a
        migrating range's new owner, and the input to its range
        digest). Host-side table walk, no device dispatch. Keys come
        back canonicalized modulo trailing NULs (the fixed-width table
        cannot represent them — same equivalence the KVS itself
        applies)."""
        self._fold(r)
        kv = self.tables[r]
        used = np.asarray(kv.used)
        keys = np.asarray(kv.keys)
        vals = np.asarray(kv.vals)
        out: List[Tuple[bytes, bytes]] = []
        for slot in np.nonzero(used)[0]:
            kb = keys[slot].astype("<i4").tobytes().rstrip(b"\x00")
            if kb < lo or (hi is not None and kb >= hi):
                continue
            out.append(
                (kb, vals[slot].astype("<i4").tobytes().rstrip(b"\x00")))
        out.sort()
        return out

    def submit_get(self, leader: int, key: bytes, *, client_id: int,
                   req_id: int) -> None:
        """The READS-THROUGH-LOG baseline: ride a stamped ``OP_GET``
        entry through the replicated log like a write — appended,
        quorum-acked, committed, folded (the dedup registry marks its
        ``req_id``, so completion is observable via ``last_req``).
        This is what every linearizable read cost before leases; the
        read-mix bench A/Bs the lease path against it."""
        self.c.submit(leader, encode_cmd(OP_GET, key).tobytes(),
                      conn=client_id, req_id=req_id)


class ClientSession:
    """A client endpoint that may RETRANSMIT requests (after a timeout, a
    reconnect, or a leader failover) — the reference's UD client whose
    duplicates the leader drops via ``last_req_id``
    (``dare_ep_db.h:20-30``, ``dare_ibv_ud.c:1004-1014``).

    Every mutation is stamped ``(client_id, req_id)`` end-to-end: the pair
    rides the entry's ``M_CONN``/``M_REQID`` columns through the log, and
    every replica's fold skips any request at-or-below the client's
    applied high-water mark — so a duplicate appended by ANY leader (the
    one that crashed after committing, or the new one the client retried
    against) applies exactly once, in first-commit order.

    PROTOCOL CONTRACT (same as the reference's single ``last_req_id``
    slot per endpoint, ``dare_ep_db.h:20-30``, and Raft client
    sessions): a session keeps AT MOST ONE request outstanding — issue
    ``put``, and if no ack arrives, ``retransmit_put`` the SAME req_id
    until it commits, before issuing the next req_id. A client that
    pipelines req N+1 before req N's fate is known can lose req N: if N
    was truncated uncommitted and N+1 commits first, the high-water mark
    passes N and every later retransmit of N is dropped as a duplicate."""

    def __init__(self, kvs: ReplicatedKVS, client_id: int):
        if client_id <= 0:
            raise ValueError("client_id must be positive")
        self.kvs = kvs
        self.client_id = client_id
        self.req_id = 0

    def put(self, leader: int, key: bytes, val: bytes) -> int:
        """Submit a PUT; returns its req_id (keep it to retransmit)."""
        self.req_id += 1
        if self.kvs.history is not None:
            self.kvs.history.invoke("put", key, val,
                                    client=self.client_id,
                                    req_id=self.req_id, replica=leader)
        spans = self.kvs._spans()
        if spans is not None:
            spans.begin(self.client_id, self.req_id,
                        self.kvs._span_rep(leader), phase="submit")
        self.kvs.put(leader, key, val, client_id=self.client_id,
                     req_id=self.req_id)
        return self.req_id

    def remove(self, leader: int, key: bytes) -> int:
        self.req_id += 1
        if self.kvs.history is not None:
            self.kvs.history.invoke("rm", key, client=self.client_id,
                                    req_id=self.req_id, replica=leader)
        spans = self.kvs._spans()
        if spans is not None:
            spans.begin(self.client_id, self.req_id,
                        self.kvs._span_rep(leader), phase="submit")
        self.kvs.remove(leader, key, client_id=self.client_id,
                        req_id=self.req_id)
        return self.req_id

    def merge(self, leader: int, op: int, key: bytes,
              val: bytes) -> int:
        """Submit a stamped mergeable write (same exactly-once
        contract as :meth:`put` — one outstanding req per session)."""
        self.req_id += 1
        if self.kvs.history is not None:
            self.kvs.history.invoke("merge", key, val,
                                    client=self.client_id,
                                    req_id=self.req_id, replica=leader)
        spans = self.kvs._spans()
        if spans is not None:
            spans.begin(self.client_id, self.req_id,
                        self.kvs._span_rep(leader), phase="submit")
        self.kvs.merge(leader, op, key, val, client_id=self.client_id,
                       req_id=self.req_id)
        return self.req_id

    def retransmit_put(self, leader: int, key: bytes, val: bytes,
                       req_id: int) -> None:
        """Resend an earlier PUT verbatim (client saw no ack — e.g. the
        leader died after commit). Safe to call any number of times."""
        if self.kvs.history is not None:
            op_id = self.kvs.history.op_id_for(self.client_id, req_id)
            if op_id is not None:
                self.kvs.history.retransmit(op_id, replica=leader)
        spans = self.kvs._spans()
        if spans is not None:
            # same (client, req) key -> same span: a retransmit is the
            # same logical command, recorded as a retransmit mark
            spans.begin(self.client_id, req_id,
                        self.kvs._span_rep(leader), phase="submit")
        self.kvs.put(leader, key, val, client_id=self.client_id,
                     req_id=req_id)
