"""Sharded multi-group consensus cluster — G independent Raft groups,
ONE compiled dispatch per step.

``SimCluster`` drives one consensus group; production-scale serving
partitions the keyspace across many. This engine stacks G independent
``(Log, HardState, peer_mask, timers)`` pytrees along a leading
``group`` axis and steps ALL of them with the group-batched protocol
step (:func:`rdma_paxos_tpu.consensus.step.group_step` — an unnamed
``vmap`` over groups around the named replica-axis ``vmap``), the way
SmartNIC replication stacks multiplex many replicated partitions onto
one device (PAPERS.md, arXiv:2503.18093). Device work per step is one
program of G× the single-group tensor shapes; host work (commit/apply
frontiers, replay, requeue, rebase, leader tracking) stays per-group.

Two execution engines behind ONE host-bookkeeping implementation:

* ``mesh=None`` (default) — the single-device engine: the group axis
  is an unnamed ``vmap`` batch axis, all G×R state on one chip.
* ``mesh=(group_shards, R)`` (or a prebuilt 2-D ``Mesh``) — the
  MULTI-CHIP engine: state is sharded ``P(group, replica)`` over a
  real ``(group, replica)`` device mesh
  (:func:`~rdma_paxos_tpu.parallel.mesh.build_mesh_2d`) and the step
  compiles via ``shard_map``
  (:func:`~rdma_paxos_tpu.parallel.mesh.build_spmd_group_step`).
  Replica collectives bind the ``replica`` mesh axis; nothing crosses
  the group axis — aggregate committed-ops/s scales with the group
  shards because each added device row carries whole extra groups
  (``benchmarks/shard_bench.py --mesh`` measures the scaling
  efficiency). The ticket contract (``begin_*``/``finish``), replay,
  rebase, and chaos hooks are byte-for-byte the same host code.

Single-group is the G=1 special case, not a parallel code path: the
same ``replica_step`` core, the same host bookkeeping rules, the same
shared compile cache (``runtime/sim.py:STEP_CACHE``) —
``tests/test_shard.py`` pins bit-identical G=1 ≡ ``SimCluster``
behavior on a recorded workload.

Fault domains: every group has its own ``peer_mask[g]`` (and optional
per-group chaos ``LinkModel``), its own elections, its own rebase
clock. Crashing one group's leader cannot disturb any other group —
the fault-isolation property the shard nemesis proves.

Leader placement: G leaderships piling onto replica 0 would make one
host the leader for every shard; :meth:`place_leaders` spreads them
round-robin (or least-loaded) across the R replicas via targeted
election timeouts.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig, REBASE_STALL_STEPS
from rdma_paxos_tpu.consensus.log import (
    EntryType, Log, M_CONN, M_GIDX, M_LEN, M_REQID, M_TYPE, META_W)
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.consensus.step import (
    SCAN_KEYS, StepInput, fetch_window)
from rdma_paxos_tpu.parallel.mesh import (
    GROUP_AXIS, REPLICA_AXIS, build_mesh_2d, build_sim_group_burst,
    build_sim_group_scan, build_sim_group_step, build_spmd_group_burst,
    build_spmd_group_scan, build_spmd_group_step, group_sharding,
    stack_group_states)
from rdma_paxos_tpu.runtime.hostpath import LazyReplayStream
from rdma_paxos_tpu.runtime.sim import (
    STEP_CACHE, SimCluster, StagingPool, StepTicket, cap_tiers,
    clamp_burst_take, decode_window, pack_rows, rebase_delta_of,
    requeue_shortfall, require_drained)
from rdma_paxos_tpu.shard.router import KeyRouter

# step() result keys pulled to host numpy each dispatch — the same set
# SimCluster materializes, so per-group slices are drop-in res dicts
_RES_KEYS = ("term", "role", "leader_id", "voted_term", "voted_for",
             "head", "apply", "commit", "end", "hb_seen",
             "became_leader", "acked", "accepted", "peer_acked",
             "leadership_verified", "rebase_delta")

TimeoutsLike = Union[None, Dict[int, Sequence[int]],
                     Sequence[Tuple[int, int]]]


class ShardedCluster:
    """G-group × R-replica protocol simulation, one dispatch per step.

    Host-bookkeeping parity ledger vs ``SimCluster`` (the per-group
    rules are the same ones, widened by a group index; any change to
    SimCluster's step/requeue/replay/rebase logic must be mirrored
    here — the G=1 bit-equivalence test in ``tests/test_shard.py``
    catches drift in everything it exercises): ``collect_frames`` /
    ``frames`` (store-ready frame assembly) and the
    ``StepPhaseProfiler`` hooks now have full parity (phase
    histograms additionally carry ``{group=g}`` apply attribution);
    ``audit=True`` mirrors SimCluster's digest auditing with
    ``(group, term, index)`` ledger keys. Unifying the two engines'
    host bookkeeping behind one helper is a ROADMAP open item."""

    K_TIERS = SimCluster.K_TIERS
    REBASE_STALL_STEPS = REBASE_STALL_STEPS

    def __init__(self, cfg: LogConfig, n_replicas: int, n_groups: int,
                 *, router: Optional[KeyRouter] = None,
                 use_pallas: Optional[bool] = None,
                 interpret: bool = False, fanout: str = "gather",
                 stable_fast_path: bool = True,
                 group_size: Optional[int] = None,
                 audit: bool = False, flight_capacity: int = 64,
                 mesh=None, telemetry: bool = False,
                 scan: bool = False, txn: bool = False):
        if n_groups < 1:
            raise ValueError("n_groups must be >= 1")
        self.cfg = cfg
        # device-resident K-window scan tier (see SimCluster.scan):
        # burst dispatches ride the fused-scan program with ONE
        # consolidated readback + in-dispatch replay rows for all
        # G x R logs. Mutable at runtime; scan-off clusters build no
        # scan programs (cache keys untouched).
        self.scan = bool(scan)
        self.scan_dispatches = 0
        self.R = int(n_replicas)
        self.G = int(n_groups)
        self.group_size = group_size or n_replicas
        self.router = (router if router is not None
                       else KeyRouter(self.G))
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._use_pallas = use_pallas
        self._interpret = interpret
        self._fanout = fanout
        self._stable_fast_path = stable_fast_path
        # mesh engine: a 2-D (group, replica) device mesh — groups
        # sharded across chips, replica collectives named on the other
        # axis. None = the single-device vmap engine (unchanged). A
        # (group_shards, replicas) tuple builds the mesh here; a
        # prebuilt jax.sharding.Mesh is used as-is. Host bookkeeping is
        # IDENTICAL either way — only the compiled dispatch differs.
        if isinstance(mesh, tuple):
            mesh = build_mesh_2d(*mesh)
        if mesh is not None:
            names = tuple(mesh.axis_names)
            if names != (GROUP_AXIS, REPLICA_AXIS):
                raise ValueError(
                    f"mesh axes must be ({GROUP_AXIS!r}, "
                    f"{REPLICA_AXIS!r}), got {names}")
            shape = mesh.devices.shape
            if shape[1] != self.R:
                raise ValueError(
                    f"mesh replica axis is {shape[1]} devices but the "
                    f"cluster has {self.R} replicas (one replica per "
                    f"chip along the replica axis)")
            if self.G % shape[0]:
                raise ValueError(
                    f"group count {self.G} must divide evenly over "
                    f"{shape[0]} group shards")
        self.mesh = mesh
        self._mode = "sim" if mesh is None else "spmd-group"
        # cache-key stand-in for the mesh: static device layout only —
        # deliberately independent of G, so clusters of ANY group
        # count on one mesh share compiled programs
        self._mesh_key = (None if mesh is None else
                          (mesh.devices.shape,
                           tuple(d.id for d in mesh.devices.flat)))
        # correctness observability (obs/audit.py): per-group digest
        # auditing keyed (group, term, index) — same mechanism as
        # SimCluster, widened by the group axis
        self._audit = audit
        if audit:
            from rdma_paxos_tpu.obs.audit import (
                AuditLedger, FlightRecorder)
            self.auditor = AuditLedger(self.R, self.G)
            self.flight = FlightRecorder(flight_capacity)
        else:
            self.auditor = None
            self.flight = None
        # device telemetry (obs/device.py) — the SimCluster mechanism
        # widened by the group axis: per-(group, replica) counter
        # vectors reduced at finish() and exported as
        # device_*{replica=,group=} series. On the mesh engine the
        # out_specs gather brings every chip's vector back into the
        # global [G, R, T_N] array, so per-shard counters survive the
        # shard_map (tests pin mesh ≡ vmap telemetry parity).
        self._telemetry = telemetry
        if telemetry:
            from rdma_paxos_tpu.obs import device as _device
            self.device_counters = _device.zeros(self.G, self.R)
        else:
            self.device_counters = None
        # cross-group transaction lane (txn/lane.py) — the SimCluster
        # mechanism widened by the group axis: per-group prepare
        # watches in the ABSOLUTE index domain (begin_step subtracts
        # each group's rebased_total), votes read back as the stacked
        # [G, R] matrix from the SAME dispatch that replicated the
        # prepares. txn=True compiles distinct serial step variants
        # (the audit=/telemetry= cache-key discipline); burst/scan
        # programs never carry the lane.
        self._txn = txn
        self._txn_watch = np.full((self.G,), -1, np.int64)
        self._txn_wterm = np.zeros((self.G,), np.int64)
        self.state = stack_group_states(cfg, self.G, self.R,
                                        self.group_size)
        if mesh is not None:
            # place the stacked state across the mesh up front so the
            # donated step never pays a layout change mid-serving
            self.state = jax.device_put(self.state,
                                        group_sharding(mesh))
        self._step_full = self._build_step(elections=True)
        # compile-count accounting: every shared-cache key this cluster
        # dispatches through (the single-compile guard's witness)
        self.programs_used: set = set()
        # device dispatch counters: protocol steps (the one-dispatch-
        # per-step claim shard_bench proves) and replay fetch sweeps
        self.dispatches = 0
        self.fetch_dispatches = 0
        self._replay_W = min(cfg.n_slots // 2,
                             max(4 * cfg.window_slots, 256))
        self._fetch_all = jax.jit(jax.vmap(jax.vmap(
            lambda log, start: fetch_window(
                log, start, window_slots=self._replay_W))))
        # ---- per-group host bookkeeping (mirrors SimCluster) ----
        G, R = self.G, self.R
        self.applied = np.zeros((G, R), np.int64)
        self.peer_mask = np.ones((G, R, R), np.int32)
        self.pending: List[List[list]] = [
            [[] for _ in range(R)] for _ in range(G)]
        # pipelined dispatch (begin_*/finish — same contract as
        # SimCluster): FIFO of in-flight tickets, staging-buffer pool,
        # host lock, dispatch-concurrency counters, dispatch clock
        self._tickets: collections.deque = collections.deque()
        self._staging = StagingPool()
        self._host_lock = threading.RLock()
        self.inflight_dispatches = 0
        self.max_inflight_dispatches = 0
        self._dispatch_clock = 0
        self.replayed: List[List[LazyReplayStream]] = [
            [LazyReplayStream() for _ in range(R)] for _ in range(G)]
        self.last: Optional[Dict[str, np.ndarray]] = None
        self.need_recovery: set = set()     # {(g, r)} force-pruned past
        self._wedged: set = set()           # {(g, r)} frozen apply
        self.rebases = np.zeros(G, np.int64)
        self.rebased_total = np.zeros(G, np.int64)
        self.rebase_stall_steps = np.zeros(G, np.int64)
        self.rebase_stalled = np.zeros(G, np.int64)
        self._prev_commit_max = np.zeros(G, np.int64)
        # optional per-group chaos link models (g -> LinkModel); purely
        # host-side input rewrites, like SimCluster.link_model
        self.link_models: Dict[int, object] = {}
        # read-path subsystem (runtime/reads.py): per-group leader
        # leases + queued read hub, observed/drained at the tail of
        # every finish() — same contract (and same attach()) as
        # SimCluster, widened by the group axis, so place_leaders
        # spreads lease-read serving across the R replicas
        self.leases = None
        self.reads = None
        # log-as-product streams hub (streams/__init__.py) — same
        # attach pattern and zero-new-STEP_CACHE-keys contract as
        # SimCluster, widened by the group axis (per-group cursors).
        self.streams = None
        # adaptive dispatch governor (runtime/governor.py) — observed
        # at the tail of every finish(), per-GROUP tier decisions over
        # the shared ladder (the dispatch uses the max rung; the
        # per-group rungs ride the trace events). Same attach pattern
        # and zero-new-STEP_CACHE-keys contract as SimCluster.
        self.governor = None
        # cross-group 2PC coordinator (txn/coordinator.py, attached
        # via txn.attach_coordinator): observed at the very tail of
        # every finish(), after the governor — same contract as
        # SimCluster. Host bookkeeping only.
        self.txn = None
        # elastic topology controller (topology/transition.py,
        # attached via topology.attach_topology): fed record
        # placements from the stamp loop (same outside-the-host-lock
        # contract as txn) and observed at the finish() tail, after
        # txn. Host bookkeeping only — zero device changes.
        self.topology = None
        # repair-held replicas barred from read serving ({(g, r)} —
        # see SimCluster.read_blocked)
        self.read_blocked: set = set()
        self.step_index = 0
        # host-side observability facade; NEVER read inside jitted code
        self.obs = None
        # optional obs.spans.StepPhaseProfiler — same hook points as
        # SimCluster (host_encode / device_dispatch / fenced sync /
        # quorum_wait / apply), plus per-group apply attribution
        # (step_phase_us{phase=apply, group=g}) recorded via self.obs
        self.profiler = None
        # store-ready framed blobs, per group per replica — byte-
        # identical to SimCluster's assembly (the G=1 parity contract);
        # only produced when a consumer opts in
        self.collect_frames = False
        self.frames: List[List[List[bytes]]] = [
            [[] for _ in range(R)] for _ in range(G)]
        # runtime lock sanitizer: the guarded-by declarations live in
        # runtime/sim.py (the fields are name-shared across both
        # engines) — under RP_SANITIZE=1 they become lock-ownership
        # assertions here too. No-op otherwise.
        from rdma_paxos_tpu.analysis import runtime_guard
        from rdma_paxos_tpu.runtime import sim as _sim_mod
        runtime_guard.maybe_guard(self, "_host_lock",
                                  _sim_mod.__file__, __file__)

    # ---------------- client-side API ----------------

    def submit(self, group: int, replica: int, payload: bytes,
               etype: EntryType = EntryType.SEND, conn: int = 1,
               req_id: int = 0) -> None:
        """Queue a client entry for the next step on ``replica`` of
        ``group`` (it only enters that group's log if the replica is
        its leader — proxy semantics, per group). Locked: a concurrent
        ``begin_*`` batch take swaps the pending list object, and an
        unlocked append to the old object would be silently lost."""
        with self._host_lock:
            self.pending[group][replica].append(
                (int(etype), conn, req_id, payload))

    def submit_many(self, group: int, replica: int,
                    entries: Sequence[Tuple[int, int, int, bytes]]
                    ) -> None:
        """Batched intake for one group's replica — see
        ``SimCluster.submit_many``."""
        with self._host_lock:
            self.pending[group][replica].extend(entries)

    def set_txn_watch(self, group: int, index: int, term: int) -> None:
        """Arm ``group``'s prepare watch: every subsequent serial step
        reports the group's per-replica vote for whether ABSOLUTE log
        index ``index`` is committed under ``term`` (txn=True clusters
        only). Sticky until cleared — the coordinator re-reads the
        ``[G, R]`` vote matrix each step while a prepare is out."""
        if not self._txn:
            raise RuntimeError("set_txn_watch requires txn=True")
        self._txn_watch[group] = int(index)
        self._txn_wterm[group] = int(term)

    def clear_txn_watch(self, group: Optional[int] = None) -> None:
        if group is None:
            self._txn_watch[:] = -1
            self._txn_wterm[:] = 0
        else:
            self._txn_watch[group] = -1
            self._txn_wterm[group] = 0

    def partition(self, group: int,
                  groups_of_replicas: Sequence[Sequence[int]]) -> None:
        """Partition ONE consensus group's replicas (other groups'
        connectivity is untouched — per-group fault domains)."""
        if self._fanout == "psum":
            raise ValueError(
                "partitions cannot be modeled with fanout='psum'; "
                "build the cluster with fanout='gather'")
        self.peer_mask[group, :, :] = 0
        for grp in groups_of_replicas:
            for i in grp:
                for j in grp:
                    self.peer_mask[group, i, j] = 1
        np.fill_diagonal(self.peer_mask[group], 1)

    def heal(self, group: Optional[int] = None) -> None:
        if group is None:
            self.peer_mask[:] = 1
        else:
            self.peer_mask[group, :, :] = 1

    def wedge_apply(self, group: int, r: int) -> None:
        self._wedged.add((group, r))

    def unwedge_apply(self, group: int, r: int) -> None:
        self._wedged.discard((group, r))

    # ---------------- stepping ----------------

    def _effective_mask(self) -> np.ndarray:
        """[G, R, R] hear-matrix: per-group base mask refined by that
        group's attached link model (host-side data only)."""
        if not self.link_models:
            return self.peer_mask
        mask = self.peer_mask.copy()
        for g, lm in self.link_models.items():
            mask[g] = lm.effective_mask(mask[g], self._dispatch_clock)
        return mask

    def _norm_timeouts(self, timeouts: TimeoutsLike) -> Dict[int, list]:
        if not timeouts:
            return {}
        if isinstance(timeouts, dict):
            return {int(g): list(rs) for g, rs in timeouts.items() if rs}
        out: Dict[int, list] = {}
        for g, r in timeouts:
            out.setdefault(int(g), []).append(int(r))
        return out

    def _step_bufs(self) -> dict:
        cfg, G, R, B = self.cfg, self.G, self.R, self.cfg.batch_slots
        return self._staging.acquire(
            ("gstep", G, R, B), lambda: dict(
                data=np.zeros((G, R, B, cfg.slot_words), np.int32),
                meta=np.zeros((G, R, B, META_W), np.int32)))

    def _burst_bufs(self, K: int) -> dict:
        cfg, G, R, B = self.cfg, self.G, self.R, self.cfg.batch_slots
        return self._staging.acquire(
            ("gburst", K, G, R, B), lambda: dict(
                data=np.zeros((K, G, R, B, cfg.slot_words), np.int32),
                meta=np.zeros((K, G, R, B, META_W), np.int32)))

    # holds-lock: _host_lock
    def reserved_appends(self) -> np.ndarray:
        """[G, R] appends dispatched but not yet finished (pipelined
        capacity reservation — same rule as SimCluster)."""
        out = np.zeros((self.G, self.R), np.int64)
        for t in self._tickets:
            for g in range(self.G):
                for r in range(self.R):
                    out[g, r] += len(t.taken[g][r])
        return out

    def _build_step(self, *, elections: bool):
        """Fetch (or compile once into the SHARED runtime cache) the
        group-batched step. The cache key carries everything static
        that shapes the program — the engine mode and (for the mesh
        engine) the static device layout — and deliberately NOT the
        group count: the jitted callable is batch-size-polymorphic, so
        every homogeneous cluster shape shares one entry per variant
        (mesh clusters of any G on one mesh included)."""
        key = (self.cfg, self.R, self._mode, self._mesh_key,
               self._use_pallas, self._interpret, self._fanout,
               "group", elections) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ()) \
            + (("txn",) if self._txn else ())
        cached = STEP_CACHE.get(key)
        if cached is None:
            kw = dict(use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      elections=elections, audit=self._audit,
                      telemetry=self._telemetry, txn=self._txn)
            if self.mesh is not None:
                cached = build_spmd_group_step(self.cfg, self.R,
                                               self.mesh, **kw)
            else:
                cached = build_sim_group_step(self.cfg, self.R, **kw)
            STEP_CACHE[key] = cached
        return cached, key

    def _burst_fn(self, K: int):
        key = (self.cfg, self.R, self._mode, self._mesh_key,
               self._use_pallas, self._interpret, self._fanout,
               "group-burst", K) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ())
        fn = STEP_CACHE.get(key)
        if fn is None:
            kw = dict(use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      audit=self._audit, telemetry=self._telemetry)
            if self.mesh is not None:
                fn = build_spmd_group_burst(self.cfg, self.R,
                                            self.mesh, **kw)
            else:
                fn = build_sim_group_burst(self.cfg, self.R, **kw)
            STEP_CACHE[key] = fn
        return fn, key

    def _scan_slots(self, K: int) -> int:
        """K-sized staged replay width — see SimCluster._scan_slots."""
        return min(self._replay_W,
                   max(K * self.cfg.batch_slots,
                       self.cfg.window_slots))

    def _scan_fn(self, K: int):
        # distinct "group-scan"-marked cache keys: scan-off clusters'
        # key sets and programs are untouched (the audit=/telemetry=
        # guard discipline; pinned by test)
        key = (self.cfg, self.R, self._mode, self._mesh_key,
               self._use_pallas, self._interpret, self._fanout,
               "group-scan", K, self._scan_slots(K)) \
            + (("audit",) if self._audit else ()) \
            + (("telemetry",) if self._telemetry else ())
        fn = STEP_CACHE.get(key)
        if fn is None:
            kw = dict(replay_slots=self._scan_slots(K),
                      use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      audit=self._audit, telemetry=self._telemetry)
            if self.mesh is not None:
                fn = build_spmd_group_scan(self.cfg, self.R,
                                           self.mesh, **kw)
            else:
                fn = build_sim_group_scan(self.cfg, self.R, **kw)
            STEP_CACHE[key] = fn
        return fn, key

    def prewarm(self, tiers: Optional[Sequence[int]] = None) -> None:
        """Compile every step variant (and burst tier) up front on
        copies of the live state. One compile covers ALL groups — the
        tiers are shared across groups by construction, and across
        clusters through the shared runtime cache."""
        cfg, G, R, B = self.cfg, self.G, self.R, self.cfg.batch_slots
        inp = StepInput(
            batch_data=jnp.zeros((G, R, B, cfg.slot_words), jnp.int32),
            batch_meta=jnp.zeros((G, R, B, META_W), jnp.int32),
            batch_count=jnp.zeros((G, R), jnp.int32),
            timeout_fired=jnp.zeros((G, R), jnp.int32),
            peer_mask=jnp.asarray(self.peer_mask),
            apply_done=jnp.zeros((G, R), jnp.int32),
            queue_depth=jnp.zeros((G, R), jnp.int32),
            **(dict(txn_watch=jnp.full((G, R), -1, jnp.int32),
                    txn_term=jnp.zeros((G, R), jnp.int32))
               if self._txn else {}))
        for elections in (True, False):
            fn, _ = self._build_step(elections=elections)
            st = jax.tree.map(lambda x: x.copy(), self.state)
            fn(st, inp)
        pm = jnp.asarray(self.peer_mask)
        ap = jnp.zeros((G, R), jnp.int32)
        for K in (tiers if tiers is not None else self.K_TIERS):
            fns = [self._burst_fn(K)]
            if self.scan:
                fns.append(self._scan_fn(K))
            for fn, _ in fns:
                st = jax.tree.map(lambda x: x.copy(), self.state)
                fn(st,
                   jnp.zeros((K, G, R, B, cfg.slot_words), jnp.int32),
                   jnp.zeros((K, G, R, B, META_W), jnp.int32),
                   jnp.zeros((K, G, R), jnp.int32), pm, ap,
                   jnp.zeros((G, R), jnp.int32))

    def begin_step(self, timeouts: TimeoutsLike = (),
                   take_batch: bool = True) -> StepTicket:
        """Encode + DISPATCH one protocol step for EVERY group in one
        device dispatch; returns the in-flight ticket immediately
        (pass to :meth:`finish`, FIFO — same pipelining contract as
        ``SimCluster.begin_step``). ``timeouts`` fires election timers
        per group: a dict ``{group: [replica, ...]}`` or an iterable
        of ``(group, replica)`` pairs."""
        cfg, G, R, B = self.cfg, self.G, self.R, self.cfg.batch_slots
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        tmo = self._norm_timeouts(timeouts)
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        bufs = self._step_bufs()
        count = np.zeros((G, R), np.int32)
        qdepth = np.zeros((G, R), np.int32)
        with self._host_lock:
            taken: List[List[list]] = [[[] for _ in range(R)]
                                       for _ in range(G)]
            for g in range(G):
                for r in range(R):
                    take = (self.pending[g][r][:B] if take_batch
                            else [])
                    if take:
                        self.pending[g][r] = self.pending[g][r][B:]
                    taken[g][r] = take
                    qdepth[g, r] = len(self.pending[g][r])
            applied = self.applied.astype(np.int32)
        for g in range(G):
            for r in range(R):
                take = taken[g][r]
                if take:
                    pack_rows(bufs, (g, r), take, cfg.slot_bytes)
                    count[g, r] = len(take)
        tmo_arr = np.zeros((G, R), np.int32)
        for g, rs in tmo.items():
            for r in rs:
                tmo_arr[g, r] = 1
        inp = StepInput(
            batch_data=jnp.asarray(bufs["data"]),
            batch_meta=jnp.asarray(bufs["meta"]),
            batch_count=jnp.asarray(count),
            timeout_fired=jnp.asarray(tmo_arr),
            peer_mask=jnp.asarray(mask),
            apply_done=jnp.asarray(applied),
            queue_depth=jnp.asarray(qdepth),
            **(dict(
                # device watches compare log offsets: shift each armed
                # ABSOLUTE index by that group's i32 rollovers, then
                # broadcast across the replica axis
                txn_watch=jnp.asarray(np.broadcast_to(
                    np.where(self._txn_watch >= 0,
                             self._txn_watch - self.rebased_total,
                             -1)[:, None], (G, R)).astype(np.int32)),
                txn_term=jnp.asarray(np.broadcast_to(
                    self._txn_wterm[:, None],
                    (G, R)).astype(np.int32)),
            ) if self._txn else {}),
        )
        # no timer fired in ANY group ⟹ Phase B is provably a no-op
        # for every group: dispatch the stable step (bit-identical)
        if self._stable_fast_path and not tmo:
            fn, key = self._build_step(elections=False)
        else:
            fn, key = self._step_full
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        with self._host_lock:
            self.state, out = fn(self.state, inp)
            ticket = StepTicket("step", out, taken, tmo, 1, bufs)
            self._tickets.append(ticket)
            self.inflight_dispatches += 1
            self.max_inflight_dispatches = max(
                self.max_inflight_dispatches, self.inflight_dispatches)
        if prof is not None:
            prof.stop("device_dispatch")
        self.dispatches += 1
        self.programs_used.add(key)
        self._dispatch_clock += 1
        return ticket

    def _tiers(self, max_k):
        """Fused tiers bounded at ``max_k`` (the shared
        ``runtime.sim.cap_tiers`` rule — one ladder, one fallback
        semantics, both engines; never a new STEP_CACHE key)."""
        return cap_tiers(self.K_TIERS, max_k)

    def begin_burst(self, max_k: Optional[int] = None) -> StepTicket:
        """Encode + DISPATCH up to ``max(K_TIERS)`` fused protocol
        steps for every group; returns the in-flight ticket. Capacity
        sizing subtracts appends reserved by other in-flight tickets
        (the pipelined clamp rule — see SimCluster.begin_burst).
        ``max_k`` caps the tier choice at a lower ladder rung (the
        governor's dial — ONE program still spans all groups, so the
        cap is the max over the per-group rungs)."""
        cfg, G, R, B = self.cfg, self.G, self.R, self.cfg.batch_slots
        assert self.last is not None, "burst requires a stepped cluster"
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        tiers = self._tiers(max_k)
        take_n = np.zeros((G, R), np.int64)
        qdepth = np.zeros((G, R), np.int32)
        taken: List[List[list]] = [[[] for _ in range(R)]
                                   for _ in range(G)]
        with self._host_lock:
            reserved = self.reserved_appends()
            last = self.last
            for g in range(G):
                for r in range(R):
                    n = clamp_burst_take(
                        len(self.pending[g][r]),
                        int(last["end"][g, r]), int(last["head"][g, r]),
                        cfg.n_slots, tiers[-1] * B,
                        int(reserved[g, r]))
                    take_n[g, r] = n
                    taken[g][r] = self.pending[g][r][:n]
                    self.pending[g][r] = self.pending[g][r][n:]
                    qdepth[g, r] = len(self.pending[g][r])
            applied = self.applied.astype(np.int32)
        k_needed = max(1, int(-(-take_n.max() // B)))
        K = next(k for k in tiers if k >= k_needed)
        bufs = self._burst_bufs(K)
        count = np.zeros((K, G, R), np.int32)
        for g in range(G):
            for r in range(R):
                n = int(take_n[g, r])
                for k in range(-(-n // B) if n else 0):
                    pack_rows(bufs, (k, g, r),
                              taken[g][r][k * B:(k + 1) * B],
                              cfg.slot_bytes)
                for k in range(K):
                    count[k, g, r] = max(0, min(n - k * B, B))
        scan = self.scan
        fn, key = self._scan_fn(K) if scan else self._burst_fn(K)
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        with self._host_lock:
            self.state, outs = fn(
                self.state, jnp.asarray(bufs["data"]),
                jnp.asarray(bufs["meta"]), jnp.asarray(count),
                jnp.asarray(mask), jnp.asarray(applied),
                jnp.asarray(qdepth))
            ticket = StepTicket("scan" if scan else "burst", outs,
                                taken, {}, K, bufs,
                                applied0=applied if scan else None)
            if scan:
                self.scan_dispatches += 1
            self._tickets.append(ticket)
            self.inflight_dispatches += 1
            self.max_inflight_dispatches = max(
                self.max_inflight_dispatches, self.inflight_dispatches)
        if prof is not None:
            prof.stop("device_dispatch")
        self.dispatches += 1
        self.programs_used.add(key)
        self._dispatch_clock += K
        return ticket

    def finish(self, ticket: StepTicket) -> Dict[str, np.ndarray]:
        """Block on ``ticket``'s outputs and run every post-step host
        rule — tickets MUST finish in dispatch order (the same
        begin/finish contract as ``SimCluster``)."""
        assert self._tickets and self._tickets[0] is ticket, \
            "tickets must finish in dispatch (FIFO) order"
        # NOT popped here — see SimCluster.finish: the ticket stays in
        # _tickets (counted by reserved_appends) until ``last`` below
        # reflects its appends, and the deque only mutates under
        # _host_lock
        G, R, B = self.G, self.R, self.cfg.batch_slots
        prof = self.profiler
        out = ticket.out
        burst = ticket.kind == "burst"
        scan = ticket.kind == "scan"
        if prof is not None:
            prof.sync(out)              # fenced device_sync (opt-in)
            prof.start("quorum_wait")
        if scan:
            # consolidated minimal readback (see SimCluster.finish)
            scal = np.asarray(out["scal"])[-1]       # [G, R, NS]
            res = {k: scal[..., i] for i, k in enumerate(SCAN_KEYS)
                   if k in _RES_KEYS}
            res["peer_acked"] = np.asarray(out["peer_acked"])[-1]
        elif burst:
            res = {k: np.asarray(getattr(out, k))[-1]
                   for k in _RES_KEYS if k != "accepted"}
            acc = np.asarray(out.accepted).sum(axis=0)       # [G, R]
            res["accepted"] = acc
        else:
            res = {k: np.asarray(getattr(out, k)) for k in _RES_KEYS}
            if self._txn and out.txn_vote is not None:
                # serial dispatches only: the txn lane never rides
                # burst/scan programs (their keys stay untouched)
                res["txn_vote"] = np.asarray(out.txn_vote)
        if prof is not None:
            prof.stop("quorum_wait")
        if self._audit:
            if burst or scan:
                get = (out.__getitem__ if scan
                       else lambda k: getattr(out, "commit"
                                              if k == "audit_commit"
                                              else k))
                a_s = np.asarray(get("audit_start"))   # [K, G, R]
                a_d = np.asarray(get("audit_digest"))  # [K, G, R, W]
                a_t = np.asarray(get("audit_term"))    # [K, G, R, W]
                a_c = np.asarray(get("audit_commit"))  # [K, G, R]
                for k in range(a_s.shape[0]):
                    self._ingest_audit(a_s[k], a_d[k], a_t[k], a_c[k])
                res["audit_start"] = a_s[-1]
                res["audit_digest"] = a_d[-1]
                res["audit_term"] = a_t[-1]
            else:
                for k in ("audit_start", "audit_digest", "audit_term"):
                    res[k] = np.asarray(getattr(out, k))
                self._ingest_audit(res["audit_start"],
                                   res["audit_digest"],
                                   res["audit_term"], res["commit"])
        if self._telemetry:
            # per-(group, replica) device counters, reduced/accumulated
            # exactly like SimCluster (finish runs on the readback
            # thread under the pipelined driver); the mesh engine's
            # out_specs gather already collected every chip's vector
            # into the global [.., G, R, T_N] array
            from rdma_paxos_tpu.obs import device as _device
            tv = np.asarray(out["telemetry"] if scan
                            else out.telemetry, dtype=np.int64)
            res["telemetry"] = (_device.reduce_steps(tv)
                                if burst or scan else tv)
            _device.accumulate(self.device_counters, res["telemetry"])
            _device.ingest(self.obs, res["telemetry"])
        txn_notes = []
        with self._host_lock:
            for g in range(G):
                for r in range(R):
                    take = ticket.taken[g][r]
                    if take and res["role"][g, r] == int(Role.LEADER):
                        acc_gr = int(res["accepted"][g, r])
                        self._stamp_appends(g, r, take, acc_gr, res)
                        if ((self.txn is not None
                             or self.topology is not None)
                                and acc_gr > 0):
                            txn_notes.append(
                                (g, r, take[:acc_gr],
                                 int(res["term"][g, r]),
                                 int(res["end"][g, r])
                                 + int(self.rebased_total[g])))
                        requeue_shortfall(self.pending[g][r], take,
                                          acc_gr)
        # coordinator/topology notification OUTSIDE _host_lock:
        # note_appends takes the coordinator (or controller) lock, and
        # client threads inside begin()/observe hold that lock while
        # submitting (which takes _host_lock) — invoking it from the
        # stamp loop would invert the coordinator -> cluster lock
        # order into an ABBA deadlock
        for note in txn_notes:
            if self.txn is not None:
                self.txn.note_appends(*note)
            if self.topology is not None:
                self.topology.note_appends(*note)
        if prof is not None:
            prof.start("apply")
        self._replay_committed(
            res, scan_rows=((out["replay_data"], out["replay_meta"],
                             ticket.applied0) if scan else None))
        if prof is not None:
            prof.stop("apply")
        if self._audit:
            self._record_flight(res, ticket.taken, ticket.timeouts,
                                burst_k=ticket.K)
        with self._host_lock:
            self._tickets.popleft()     # retire: last now covers it
            self.inflight_dispatches -= 1
            # the per-group i32 rollover rewrites offsets host-side:
            # deferred while dispatches are in flight (see SimCluster)
            if not self._tickets:
                self._maybe_rebase(res)
            self.last = res
        self.step_index += ticket.K
        self._observe(res)
        # read path: per-group lease renew/revoke from the finished
        # step, then serve due queued reads (readback thread under
        # the pipelined driver — same contract as SimCluster)
        if self.leases is not None:
            self.leases.observe(self, res)
        if self.reads is not None:
            self.reads.drain(self)
        if self.streams is not None:
            self.streams.observe(self, res)
        if self.governor is not None:
            self.governor.observe(self, res)
        if self.txn is not None:
            self.txn.observe(self, res)
        if self.topology is not None:
            self.topology.observe(self, res)
        if burst or scan:
            self._staging.release(ticket.bufs, [
                ((k, g, r), min(B, len(t) - k * B))
                for g in range(G) for r in range(R)
                for t in (ticket.taken[g][r],)
                for k in range(-(-len(t) // B) if t else 0)])
        else:
            self._staging.release(ticket.bufs, [
                ((g, r), len(ticket.taken[g][r]))
                for g in range(G) for r in range(R)])
        return res

    def drain(self) -> Optional[Dict[str, np.ndarray]]:
        """Finish every in-flight ticket in order; returns the final
        result (or None when nothing was in flight)."""
        res = None
        while self._tickets:
            res = self.finish(self._tickets[0])
        return res

    def step(self, timeouts: TimeoutsLike = ()) -> Dict[str, np.ndarray]:
        """One protocol step for EVERY group in one device dispatch.
        ``timeouts`` fires election timers per group: a dict
        ``{group: [replica, ...]}`` or an iterable of ``(group,
        replica)`` pairs. Returns ``[G, R]`` result arrays."""
        require_drained(self._tickets, "step")
        return self.finish(self.begin_step(timeouts))

    def step_burst(self, max_k: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        """Drain every group's pending queues through up to
        ``max(K_TIERS)`` fused protocol steps in ONE device dispatch.
        Same contract as ``SimCluster.step_burst`` per group: no
        elections fire inside the burst; the caller must only burst
        while every trafficked group has a known leader. ``max_k``
        caps the tier (the governor's dial)."""
        require_drained(self._tickets, "step_burst")
        return self.finish(self.begin_burst(max_k=max_k))

    # ---------------- host apply / rebase ----------------

    def _replay_committed(self, res, scan_rows=None) -> None:
        """Per-group host apply loop — ALL groups' and replicas'
        windows ride ONE fetch dispatch per sweep (the [G, R]-vmapped
        ``fetch_window``). Same integrity rule as ``SimCluster``: a
        fetched entry whose stamped gidx disagrees with the expected
        apply index means the slot was recycled past this member —
        flag ``(g, r)`` for snapshot recovery and stop replaying.
        Frame assembly and the per-group apply-time histograms
        (``step_phase_us{phase=apply, group=g}``) ride the same decode
        pass. ``scan_rows``: the K-window scan tier's in-dispatch
        replay rows, consumed FIRST (see SimCluster) — a scan whose
        commit delta fits the staged window pays zero fetch
        dispatches."""
        import time as _time
        W = self._replay_W
        t_group: Dict[int, int] = {}
        if scan_rows is not None:
            wd_fut, wm_fut, applied0 = scan_rows
            staged = int(wm_fut.shape[-2])     # K-sized, <= replay_W
            wd_all = wm_all = None
            for g in range(self.G):
                for r in range(self.R):
                    if ((g, r) in self._wedged
                            or (g, r) in self.need_recovery):
                        continue
                    commit = int(res["commit"][g, r])
                    off = int(self.applied[g, r]) - int(applied0[g, r])
                    n = int(min(commit - self.applied[g, r],
                                staged - off))
                    if n <= 0 or off < 0:
                        continue
                    if wd_all is None:  # lazy: transfer only if used
                        wd_all = np.asarray(wd_fut)
                        wm_all = np.asarray(wm_fut)
                    t0 = _time.perf_counter_ns()
                    wd = wd_all[g, r, off:off + n]
                    wm = wm_all[g, r, off:off + n]
                    if int(wm[0, M_GIDX]) != self.applied[g, r]:
                        self.need_recovery.add((g, r))
                        continue
                    decode_window(wm, wd, n, self.replayed[g][r],
                                  self.frames[g][r],
                                  self.collect_frames,
                                  rebase=int(self.rebased_total[g]))
                    self.applied[g, r] += n
                    t_group[g] = (t_group.get(g, 0)
                                  + _time.perf_counter_ns() - t0)
        while True:
            todo = [(g, r) for g in range(self.G)
                    for r in range(self.R)
                    if (g, r) not in self._wedged
                    and (g, r) not in self.need_recovery
                    and self.applied[g, r] < int(res["commit"][g, r])]
            if not todo:
                break
            starts = jnp.asarray(self.applied.astype(np.int32))
            # bind under the host lock (donation hazard — see
            # SimCluster._replay_committed); block on results outside it
            with self._host_lock:
                wd_fut, wm_fut = self._fetch_all(self.state.log, starts)
            self.fetch_dispatches += 1
            wd_all, wm_all = np.asarray(wd_fut), np.asarray(wm_fut)
            for g, r in todo:
                t0 = _time.perf_counter_ns()
                commit = int(res["commit"][g, r])
                n = int(min(commit - self.applied[g, r], W))
                wd, wm = wd_all[g, r], wm_all[g, r]
                if n > 0 and int(wm[0, M_GIDX]) != self.applied[g, r]:
                    self.need_recovery.add((g, r))
                    continue
                decode_window(wm, wd, n, self.replayed[g][r],
                              self.frames[g][r], self.collect_frames,
                              rebase=int(self.rebased_total[g]))
                self.applied[g, r] += n
                t_group[g] = (t_group.get(g, 0)
                              + _time.perf_counter_ns() - t0)
        if (t_group and self.obs is not None
                and self.profiler is not None):
            from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_US
            for g, ns in sorted(t_group.items()):
                self.obs.metrics.observe(
                    "step_phase_us", ns / 1e3,
                    buckets=LATENCY_BUCKETS_US, phase="apply", group=g)

    def _rebase_stalled_step(self, g: int, res) -> None:
        self.rebase_stall_steps[g] += 1
        if self.rebase_stall_steps[g] < self.REBASE_STALL_STEPS:
            return
        self.rebase_stalled[g] += 1
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("rebase_stalled", group=g)
            if self.rebase_stall_steps[g] == self.REBASE_STALL_STEPS:
                heads = [int(res["head"][g, r]) for r in range(self.R)]
                self.obs.trace.record(
                    _trace.REBASE_STALLED, group=g,
                    end_max=int(res["end"][g].max()),
                    threshold=self.cfg.rebase_threshold,
                    min_head=min(heads), heads=heads,
                    steps=int(self.rebase_stall_steps[g]))

    # holds-lock: _host_lock
    def _maybe_rebase(self, res) -> None:
        """Per-group coordinated i32-offset rollover: each group whose
        max end crossed ``rebase_threshold`` drops every offset of ITS
        replicas by its own min head (rounded down to a multiple of
        n_slots) — other groups' offsets are untouched. All crossing
        groups shift in one elementwise pass. ``res`` is adjusted in
        place so callers observe post-rollover offsets."""
        ends = res["end"].max(axis=1)                       # [G]
        if ends.max() < self.cfg.rebase_threshold:
            return
        deltas = np.zeros(self.G, np.int64)
        for g in range(self.G):
            if ends[g] < self.cfg.rebase_threshold:
                continue
            heads = [int(res["head"][g, r]) for r in range(self.R)
                     if (g, r) not in self.need_recovery]
            delta = rebase_delta_of(heads, self.cfg.n_slots)
            if delta <= 0:
                self._rebase_stalled_step(g, res)
                continue
            deltas[g] = delta
        if not deltas.any():
            return
        self._apply_rebase(deltas)
        for g in np.nonzero(deltas)[0]:
            d = int(deltas[g])
            self.applied[g] -= d
            for k in ("head", "apply", "commit", "end"):
                res[k][g] = res[k][g] - d
            # keep the returned dict self-consistent: audit_start is
            # an index too (the ledger already ingested pre-rollover)
            if "audit_start" in res:
                res["audit_start"][g] = res["audit_start"][g] - d
            self.rebases[g] += 1
            self.rebased_total[g] += d
            self.rebase_stall_steps[g] = 0
            if self.obs is not None:
                from rdma_paxos_tpu.obs import trace as _trace
                self.obs.metrics.inc("rebases_total", group=int(g))
                self.obs.metrics.inc("rebased_entries_total", d,
                                     group=int(g))
                self.obs.trace.record(_trace.REBASE_APPLIED,
                                      group=int(g), delta=d,
                                      rebases=int(self.rebases[g]))

    # holds-lock: _host_lock
    def _apply_rebase(self, deltas: np.ndarray) -> None:
        """Elementwise per-group offset subtraction — the grouped form
        of ``consensus.snapshot.rebase_offsets`` (same invariants:
        delta <= that group's min head, multiple of n_slots). Called
        from ``_maybe_rebase`` under the host lock."""
        state = self.state
        d_gr = jnp.asarray(deltas.astype(np.int32))[:, None]   # [G, 1]
        d_buf = d_gr[:, :, None]                               # [G, 1, 1]
        sw = state.log.slot_words
        gcol = sw + M_GIDX
        buf = state.log.buf.at[..., gcol].add(-d_buf)
        self.state = dataclasses.replace(
            state,
            log=Log(buf=buf),
            head=state.head - d_gr,
            apply=state.apply - d_gr,
            commit=state.commit - d_gr,
            end=state.end - d_gr,
            cfg_src=jnp.where(state.cfg_src >= 0,
                              state.cfg_src - d_gr, state.cfg_src),
        )
        if self.mesh is not None:
            # the eager elementwise pass may leave drifted shardings;
            # re-place so the next donated dispatch pays no reshard
            # (rebases are rare — deferred until the pipeline drains)
            self.state = jax.device_put(self.state,
                                        group_sharding(self.mesh))

    # ---------------- observability ----------------

    def redigest(self, group: int, replica: int, lo: int,
                 hi: int) -> int:
        """Range re-digest backfill for ONE group's replica (raw
        offsets of that group) — the per-group form of
        ``SimCluster.redigest``; other groups' state is untouched and
        their dispatches resume as soon as this drained serial pass
        returns. Shares the jitted redigest program (and its
        ``"redigest"``-marked cache key) with the single-group
        engine."""
        from rdma_paxos_tpu.runtime.sim import run_redigest
        return run_redigest(
            self, self.state.log.buf[group, replica], lo, hi,
            group=group, rebased_total=int(self.rebased_total[group]),
            replica=replica)

    def _ingest_audit(self, starts, digests, terms, commits) -> None:
        """Per-group digest ingestion: ledger keys are ``(group,
        absolute index)`` with each group's own ``rebased_total``
        correction (groups rebase independently). Runs before
        ``_maybe_rebase`` so raw offsets and corrections agree."""
        led = self.auditor
        led.obs = self.obs
        W = self.cfg.window_slots
        for g in range(self.G):
            reb = int(self.rebased_total[g])
            s_l = starts[g].tolist()
            c_l = commits[g].tolist()
            for r in range(self.R):
                start, commit = s_l[r], c_l[r]
                n = commit - start
                if n <= 0:
                    continue
                off = start - (commit - W)
                led.record_window(r, start + reb,
                                  digests[g, r, off:off + n],
                                  terms[g, r, off:off + n],
                                  commit + reb, group=g,
                                  step=self.step_index)

    def _record_flight(self, res, taken, tmo, burst_k: int = 1) -> None:
        """Same contract as ``SimCluster._record_flight``, widened by
        the group axis; arrays are copied (the sharded rebase mutates
        ``res`` rows in place after this runs)."""
        entry = dict(
            step=self.step_index, burst_k=burst_k,
            timeouts={int(g): [int(r) for r in rs]
                      for g, rs in dict(tmo).items()},
            rebased_total=self.rebased_total.copy(),
            inputs=taken,
            outputs={k: res[k].copy()
                     for k in ("term", "role", "leader_id", "head",
                               "apply", "commit", "end", "accepted")},
            applied=self.applied.copy(),
            digests=dict(start=res["audit_start"].copy(),
                         commit=res["commit"].copy(),
                         window=res["audit_digest"]))
        self.flight.record(entry)

    def _span_recorder(self):
        from rdma_paxos_tpu.obs.spans import active_recorder
        return active_recorder(self.obs)

    def _span_rep(self, g: int, r: int) -> int:
        """Namespaced span replica id: per-group frontiers must not
        collide in the recorder's per-replica heaps."""
        return g * self.R + r

    def _stamp_appends(self, g: int, r: int, take, acc: int,
                       res) -> None:
        """The accepted prefix of ``take`` landed at absolute indices
        ``[end-acc, end)`` on group ``g``'s leader ``r`` — stamp each
        sampled span with its ``(group, term, index)`` key."""
        spans = self._span_recorder()
        if spans is None or not spans.open_count or acc <= 0:
            return
        end_abs = int(res["end"][g, r]) + int(self.rebased_total[g])
        term = int(res["term"][g, r])
        replicas = [self._span_rep(g, rr) for rr in range(self.R)]
        for i, (_t, conn, req, _p) in enumerate(take[:acc]):
            spans.stamp_append(conn, req, term, end_abs - acc + i,
                               self._span_rep(g, r), replicas=replicas,
                               group=g)

    def _observe(self, res) -> None:
        """Per-group metric gauges/counters (``...{group=g}`` series)
        plus span commit/apply frontier advance. Host-side only."""
        spans = self._span_recorder()
        if spans is not None and spans.open_count:
            for g in range(self.G):
                rebased = int(self.rebased_total[g])
                for r in range(self.R):
                    rep = self._span_rep(g, r)
                    spans.commit_advance(
                        rep, int(res["commit"][g, r]) + rebased)
                    spans.apply_advance(
                        rep, int(self.applied[g, r]) + rebased)
        if self.obs is None:
            return
        m = self.obs.metrics
        for g in range(self.G):
            rebased = int(self.rebased_total[g])
            cmax = int(res["commit"][g].max()) + rebased
            m.set("shard_term", int(res["term"][g].max()), group=g)
            m.set("shard_commit", cmax, group=g)
            m.set("shard_apply",
                  int(self.applied[g].min()) + rebased, group=g)
            m.set("shard_leader", self.leader_hint(g), group=g)
            delta = cmax - int(self._prev_commit_max[g])
            if delta > 0:
                m.inc("shard_committed_entries_total", delta, group=g)
            self._prev_commit_max[g] = cmax

    def health(self) -> dict:
        """Aggregated sharded-cluster health: one snapshot per group
        (per-replica offsets/roles, rebase counters, recovery flags)
        plus the serialized ROUTER — the full routing table rides the
        health document so any observer reconstructs the exact
        key→group mapping without code."""
        from rdma_paxos_tpu.obs.health import make_snapshot
        res = self.last
        groups = []
        for g in range(self.G):
            fields = dict(
                group=g,
                leader=self.leader_hint(g),
                rebases=int(self.rebases[g]),
                rebased_total=int(self.rebased_total[g]),
                rebase_stalled=int(self.rebase_stalled[g]),
                need_recovery=sorted(r for (gg, r) in self.need_recovery
                                     if gg == g),
                applied=[int(a) for a in self.applied[g]],
            )
            if res is not None:
                for k in ("role", "term", "commit", "apply", "end",
                          "head"):
                    fields[k] = [int(v) for v in res[k][g]]
                fields["log_headroom"] = int(
                    self.cfg.rebase_threshold - res["end"][g].max())
            groups.append(make_snapshot(**fields))
        return dict(schema=1, n_groups=self.G, n_replicas=self.R,
                    dispatches=self.dispatches,
                    engine=self._mode,
                    mesh=(None if self.mesh is None else
                          dict(layout="%dx%d" % self.mesh.devices.shape,
                               group_shards=int(self.mesh.devices.shape[0]),
                               devices=[int(d.id)
                                        for d in self.mesh.devices.flat])),
                    router=self.router.to_dict(), groups=groups,
                    audit=(self.auditor.summary()
                           if self.auditor is not None else None),
                    leases=(self.leases.status()
                            if self.leases is not None else None),
                    topology=(self.topology.status()
                              if self.topology is not None else None))

    # ---------------- leadership ----------------

    def leader(self, group: int) -> int:
        """Group ``group``'s leader iff exactly one replica claims it
        (the strict ``SimCluster.leader`` rule), else -1."""
        assert self.last is not None
        ids = [r for r in range(self.R)
               if self.last["role"][group, r] == int(Role.LEADER)]
        return ids[0] if len(ids) == 1 else -1

    def leader_hint(self, group: int) -> int:
        """Highest-term self-claimed leader of ``group`` (the driver's
        failover view rule — terms are unique per leader), or -1."""
        if self.last is None:
            return -1
        claims = [(int(self.last["term"][group, r]), r)
                  for r in range(self.R)
                  if int(self.last["role"][group, r]) == int(Role.LEADER)]
        return max(claims)[1] if claims else -1

    def leaders(self) -> List[int]:
        return [self.leader_hint(g) for g in range(self.G)]

    def run_until_elected(self, group: int, candidate: int,
                          max_steps: int = 5) -> int:
        for _ in range(max_steps):
            res = self.step(timeouts={group: [candidate]})
            if res["role"][group, candidate] == int(Role.LEADER):
                return candidate
        raise AssertionError(
            f"election did not converge in group {group}")

    def place_leaders(self, policy: str = "round_robin",
                      max_steps: int = 12) -> List[int]:
        """Elect a leader in EVERY group, spread across the R replicas
        so the G leaderships don't pile onto replica 0.

        * ``round_robin`` — group g targets replica ``g % R``.
        * ``least_loaded`` — each group targets the replica currently
          holding the fewest leaderships (existing leaders counted
          first, then assignments made greedily in group order).

        Elections for different groups ride the SAME dispatches — the
        whole placement typically converges in one or two steps.
        Returns the per-group target list."""
        if policy == "round_robin":
            targets = [g % self.R for g in range(self.G)]
        elif policy == "least_loaded":
            load = [0] * self.R
            targets = [-1] * self.G
            for g in range(self.G):
                cur = self.leader_hint(g) if self.last is not None else -1
                if cur >= 0:
                    targets[g] = cur
                    load[cur] += 1
            for g in range(self.G):
                if targets[g] < 0:
                    t = int(np.argmin(load))
                    targets[g] = t
                    load[t] += 1
        else:
            raise ValueError(f"unknown placement policy: {policy!r}")
        for _ in range(max_steps):
            pending = {g: [targets[g]] for g in range(self.G)
                       if self.last is None
                       or self.leader(g) != targets[g]}
            if not pending:
                return targets
            self.step(timeouts=pending)
        undone = [g for g in range(self.G)
                  if self.leader(g) != targets[g]]
        if undone:
            raise AssertionError(
                f"leader placement did not converge for groups {undone}")
        return targets
