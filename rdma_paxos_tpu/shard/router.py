"""Deterministic key→group routing for the sharded multi-group cluster.

The reference scales by running one consensus group per application;
the sharded layer partitions ONE application's keyspace across many
independent groups instead (the way reconfigurable commit protocols
shard state across replica groups — PAPERS.md, arXiv:1906.01365). The
router is the contract every client, proxy, and operator tool must
agree on, so it is built from primitives that are stable across
process restarts, machines, and Python versions:

* a **hash ring**: each of the ``n_groups`` groups owns ``vnodes``
  points on a 32-bit ring, placed by :func:`ring_hash` (FNV-1a mixed
  through the Murmur3 finalizer — never Python's salted ``hash()``)
  over a canonical label; a key routes to the successor point of its
  own :func:`ring_hash`. The group COUNT stays fixed (G is baked into
  the compiled dispatch); elastic split/merge (``topology/``)
  reshapes routing by installing/removing override rules through the
  mutation surface below, bumping ``version`` at each cutover.
* an explicit **range-override table**: ordered ``(lo, hi, group)``
  rules on raw key bytes (``lo <= key < hi``, lexicographic;
  ``hi=None`` = unbounded). First matching rule wins and overrides
  take precedence over the ring — the operator's escape hatch for hot
  ranges, locality pinning, and migration staging.

Keys are raw bytes; ``str`` keys are accepted and canonicalized as
UTF-8. The empty key is a valid key (it hashes to the FNV offset
basis). The full routing table serializes to a plain dict
(:meth:`KeyRouter.to_dict`) that rides the sharded cluster's health
snapshots, so any observer can reconstruct the exact mapping without
importing this module's code — and ``tests/golden/router_map.json``
pins the mapping across releases.
"""

from __future__ import annotations

import bisect
from typing import List, Optional, Sequence, Tuple, Union

KeyLike = Union[bytes, bytearray, str]

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def fnv1a32(data: bytes) -> int:
    """32-bit FNV-1a — stable by construction (pure arithmetic over
    bytes), unlike Python's per-process-salted ``hash``; golden-file
    tested across restarts."""
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFF
    return h


def _fmix32(h: int) -> int:
    """Murmur3's 32-bit finalizer. Raw FNV-1a has weak avalanche in
    the high bits — sequential keys (``k0``, ``k1``, ...) cluster on
    the ring and skew group load badly; one finalizer round spreads
    them. Pure arithmetic, restart-stable."""
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def ring_hash(data: bytes) -> int:
    """The router's placement hash: FNV-1a mixed through the Murmur3
    finalizer — used for both ring points and keys."""
    return _fmix32(fnv1a32(data))


def canon_key(key: KeyLike) -> bytes:
    """Canonical key bytes: bytes pass through, ``str`` encodes UTF-8.
    The empty key is legal (it routes like any other)."""
    if isinstance(key, str):
        return key.encode("utf-8")
    if isinstance(key, (bytes, bytearray)):
        return bytes(key)
    raise TypeError(f"key must be bytes or str, not {type(key).__name__}")


class RangeRule:
    """One override: keys in ``[lo, hi)`` (byte-lexicographic; ``hi``
    ``None`` = +inf) route to ``group``, bypassing the ring."""

    __slots__ = ("lo", "hi", "group")

    def __init__(self, lo: KeyLike, hi: Optional[KeyLike], group: int):
        self.lo = canon_key(lo)
        self.hi = canon_key(hi) if hi is not None else None
        self.group = int(group)
        if self.hi is not None and self.hi <= self.lo:
            raise ValueError(f"empty range: lo={self.lo!r} hi={self.hi!r}")

    def matches(self, key: bytes) -> bool:
        return key >= self.lo and (self.hi is None or key < self.hi)

    def to_dict(self) -> dict:
        return dict(lo=self.lo.hex(),
                    hi=self.hi.hex() if self.hi is not None else None,
                    group=self.group)

    @classmethod
    def from_dict(cls, d: dict) -> "RangeRule":
        return cls(bytes.fromhex(d["lo"]),
                   bytes.fromhex(d["hi"]) if d["hi"] is not None else None,
                   d["group"])

    def __eq__(self, other) -> bool:
        return (isinstance(other, RangeRule) and self.lo == other.lo
                and self.hi == other.hi and self.group == other.group)

    def __hash__(self) -> int:
        return hash((self.lo, self.hi, self.group))

    def __repr__(self) -> str:
        return f"RangeRule({self.lo!r}, {self.hi!r}, {self.group})"


class KeyRouter:
    """Hash-ring + range-override key→group mapping (see module doc).

    Deterministic: ``group_of`` is a pure function of (key, n_groups,
    vnodes, overrides). The override table is the ONE mutable part —
    ``install_rule``/``remove_rule`` swap the whole list atomically
    (one reference assignment; concurrent ``group_of`` readers see
    the old table or the new, never a partial edit) and bump
    ``version``, the monotone counter topology cutovers fence txn
    admissions and serialized snapshots against.
    """

    def __init__(self, n_groups: int, *, vnodes: int = 64,
                 overrides: Sequence[Union[RangeRule, tuple]] = ()):
        if n_groups < 1:
            raise ValueError("n_groups must be >= 1")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.n_groups = int(n_groups)
        self.vnodes = int(vnodes)
        self.version = 0
        self.overrides: List[RangeRule] = [
            r if isinstance(r, RangeRule) else RangeRule(*r)
            for r in overrides]
        for r in self.overrides:
            if not (0 <= r.group < self.n_groups):
                raise ValueError(
                    f"override group {r.group} out of range "
                    f"[0, {self.n_groups})")
        # ring points: FNV-1a of a canonical label per (group, vnode).
        # A 32-bit collision between two groups' points is resolved by
        # the (point, group) sort order — deterministically, the lower
        # group id wins the shared point.
        ring: List[Tuple[int, int]] = []
        for g in range(self.n_groups):
            for v in range(self.vnodes):
                ring.append((ring_hash(b"group:%d:vnode:%d" % (g, v)), g))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]

    # ---------------- routing ----------------

    def group_of(self, key: KeyLike) -> int:
        """The group serving ``key``: first matching range override,
        else the ring successor of the key's hash (wrapping)."""
        kb = canon_key(key)
        for rule in self.overrides:
            if rule.matches(kb):
                return rule.group
        h = ring_hash(kb)
        i = bisect.bisect_left(self._points, h)
        if i == len(self._points):
            i = 0                           # wrap to the ring start
        return self._ring[i][1]

    # ---------------- mutation (topology transitions) ----------------

    def _coerce(self, rule: Union[RangeRule, tuple]) -> RangeRule:
        r = rule if isinstance(rule, RangeRule) else RangeRule(*rule)
        if not (0 <= r.group < self.n_groups):
            raise ValueError(
                f"override group {r.group} out of range "
                f"[0, {self.n_groups})")
        return r

    def with_rule(self, rule: Union[RangeRule, tuple]) -> "KeyRouter":
        """CANDIDATE router: this one plus ``rule`` PREPENDED (first
        match wins, so the new rule beats any older overlapping rule
        — same precedence ``install_rule`` later gives it). The
        transition window routes donor/target decisions by diffing
        this candidate against the live router; nothing serves it."""
        r = self._coerce(rule)
        return KeyRouter(self.n_groups, vnodes=self.vnodes,
                         overrides=[r] + list(self.overrides))

    def without_rule(self, rule: Union[RangeRule, tuple]) -> "KeyRouter":
        """CANDIDATE router with the first override equal to ``rule``
        dropped — the merge direction of :meth:`with_rule`."""
        r = self._coerce(rule)
        rest = list(self.overrides)
        rest.remove(r)             # ValueError if absent — caller bug
        return KeyRouter(self.n_groups, vnodes=self.vnodes,
                         overrides=rest)

    def install_rule(self, rule: Union[RangeRule, tuple]) -> int:
        """Cutover: prepend ``rule`` to the live table (atomic list
        swap) and bump ``version``. Returns the new version."""
        r = self._coerce(rule)
        self.overrides = [r] + list(self.overrides)
        self.version += 1
        return self.version

    def remove_rule(self, rule: Union[RangeRule, tuple]) -> int:
        """Cutover (merge direction): drop the first override equal to
        ``rule`` (atomic list swap) and bump ``version``."""
        r = self._coerce(rule)
        rest = list(self.overrides)
        rest.remove(r)             # ValueError if absent — caller bug
        self.overrides = rest
        self.version += 1
        return self.version

    # ---------------- serialization (health snapshots) ----------------

    def to_dict(self) -> dict:
        """Plain-data routing table for health snapshots and golden
        files: everything needed to reconstruct the mapping, plus a
        ring checksum so observers can verify agreement without
        rebuilding the ring."""
        ck = _FNV_OFFSET
        for p, g in self._ring:
            for b in p.to_bytes(4, "big") + bytes([g & 0xFF]):
                ck = ((ck ^ b) * _FNV_PRIME) & 0xFFFFFFFF
        return dict(schema=1, kind="hash_ring", n_groups=self.n_groups,
                    vnodes=self.vnodes, hash="fnv1a32+fmix32",
                    ring_checksum=ck, version=self.version,
                    overrides=[r.to_dict() for r in self.overrides])

    @classmethod
    def from_dict(cls, d: dict) -> "KeyRouter":
        if (d.get("kind") != "hash_ring"
                or d.get("hash") != "fnv1a32+fmix32"):
            raise ValueError(f"unknown router serialization: {d!r}")
        router = cls(d["n_groups"], vnodes=d["vnodes"],
                     overrides=[RangeRule.from_dict(o)
                                for o in d["overrides"]])
        want = d.get("ring_checksum")
        have = router.to_dict()["ring_checksum"]
        if want is not None and want != have:
            raise ValueError(
                f"router ring checksum mismatch: snapshot {want} != "
                f"rebuilt {have} (incompatible router versions?)")
        # pre-elastic snapshots carry no version — reconstruct as 0
        router.version = int(d.get("version", 0))
        return router

    def __repr__(self) -> str:
        return (f"KeyRouter(n_groups={self.n_groups}, "
                f"vnodes={self.vnodes}, "
                f"overrides={len(self.overrides)})")
