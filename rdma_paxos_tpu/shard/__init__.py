"""Sharded multi-group consensus — the layer above the single-group
stack that partitions a keyspace across G independent Raft groups.

* :mod:`~rdma_paxos_tpu.shard.router` — deterministic key→group
  mapping: FNV-1a hash ring (fixed group count) + explicit
  range-override table; serialized into health snapshots.
* :mod:`~rdma_paxos_tpu.shard.cluster` — :class:`ShardedCluster`:
  G × R state stacked along a leading ``group`` axis, every group
  stepped by ONE compiled dispatch (the group-batched
  ``consensus.step.group_step``); per-group commit/apply frontiers,
  elections, rebase, and fault domains on the host side; leader
  placement spreading G leaderships across the R replicas.
* :mod:`~rdma_paxos_tpu.shard.kvs` — :class:`ShardedKVS` +
  :class:`ShardedSession`: routed puts/gets/removes, per-group dedup
  sequence numbers, per-group leader failover.
* :mod:`~rdma_paxos_tpu.shard.chaos` — :class:`ShardNemesisRunner`:
  crash one group's leader, prove the other groups never notice
  (I1–I5 per group + strict frontier advance).

Single-group remains the G=1 special case of this machinery —
``tests/test_shard.py`` pins bit-identical behavior against
``SimCluster`` — and G groups sharing one ``LogConfig`` share one
compiled step through the runtime's shared cache.
"""

from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS, ShardedSession
from rdma_paxos_tpu.shard.router import KeyRouter, RangeRule, fnv1a32

__all__ = ["ShardedCluster", "ShardedKVS", "ShardedSession",
           "KeyRouter", "RangeRule", "fnv1a32"]
