"""Shard nemesis — fault isolation across consensus groups, proven.

The single-group :class:`~rdma_paxos_tpu.chaos.runner.NemesisRunner`
answers "does one group survive faults?"; the sharded layer must also
answer "does a fault in one group stay IN that group?". This runner
drives a :class:`~rdma_paxos_tpu.shard.cluster.ShardedCluster` +
:class:`~rdma_paxos_tpu.shard.kvs.ShardedKVS` workload, crashes the
leader of ONE target group mid-run (fail-stop via the chaos
subsystem's :class:`~rdma_paxos_tpu.chaos.faults.LinkModel`, attached
to that group only), re-elects after a timeout, and verdicts:

* the existing **I1–I5 protocol invariants hold PER GROUP** — one
  :class:`~rdma_paxos_tpu.chaos.invariants.InvariantChecker` per
  group over that group's ``[R]`` result slices, convergence checked
  over that group's replay streams;
* the untouched groups' **commit frontiers keep strictly advancing
  through the victim group's outage** (fault isolation — the whole
  point of per-group fault domains);
* the victim group **recovers** (new leader, frontier advances again)
  without any other group noticing.

Determinism: all randomness derives from the run seed; time is the
logical step counter — same seed, same verdict (the chaos
subsystem's reproducibility contract).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from rdma_paxos_tpu.chaos.faults import LinkModel
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history
from rdma_paxos_tpu.chaos.runner import DEFAULT_KV_CFG
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS


def keys_for_groups(router, per_group: int,
                    prefix: bytes = b"key") -> List[List[bytes]]:
    """Deterministically enumerate ``prefix%d`` keys until every group
    owns ``per_group`` of them — the seeded workload's routing table."""
    out: List[List[bytes]] = [[] for _ in range(router.n_groups)]
    i = 0
    while any(len(ks) < per_group for ks in out):
        key = prefix + b"%d" % i
        g = router.group_of(key)
        if len(out[g]) < per_group:
            out[g].append(key)
        i += 1
        if i > 100000:
            raise RuntimeError("router starved a group of keys")
    return out


class ShardNemesisRunner:
    """One seeded leader-crash run over a fresh sharded cluster."""

    def __init__(self, cfg: Optional[LogConfig] = None,
                 n_replicas: int = 3, n_groups: int = 4, *,
                 seed: int = 0, steps: int = 60, crash_step: int = 20,
                 reelect_after: int = 4, target_group: int = 0,
                 settle_steps: int = 12, keys_per_group: int = 2,
                 obs=None, audit: bool = True, leases: bool = True,
                 read_patience: int = 12):
        self.cfg = cfg or DEFAULT_KV_CFG
        self.R, self.G = int(n_replicas), int(n_groups)
        self.seed = int(seed)
        self.steps = int(steps)
        self.crash_step = int(crash_step)
        self.reelect_after = int(reelect_after)
        self.target = int(target_group)
        self.settle_steps = int(settle_steps)
        # audit at 100% by default: a passing shard nemesis also proves
        # bit-identical per-group replicated state through the outage
        self.shard = ShardedCluster(self.cfg, self.R, self.G,
                                    audit=audit)
        if obs is None:
            # runner-owned facade: the read-path accounting
            # (reads_served_total{path=}) and lease timeline need a
            # registry/trace ring to land in
            from rdma_paxos_tpu.obs import Observability
            obs = Observability()
        self.obs = obs
        self.shard.obs = obs
        self.kv = ShardedKVS(self.shard, cap=256)
        # the fault domain is ONE group: the link model is attached to
        # the target group only — other groups' masks are never touched
        self.link = LinkModel(self.R, seed=seed)
        self.shard.link_models[self.target] = self.link
        self.checkers = [InvariantChecker(self.R)
                         for _ in range(self.G)]
        self.keys = keys_for_groups(self.kv.router, keys_per_group)
        self.rng = random.Random(f"shard-nemesis:{seed}")
        self._vn = 0
        # client-visible contract checking: every session write and
        # every linearizable read (lease AND read-index paths,
        # runtime/reads.py) is recorded into ONE history the per-key
        # Wing–Gong checker verdicts — the sharded analog of the
        # single-group NemesisRunner's acceptance bar
        self.history = HistoryRecorder()
        for g in range(self.G):
            self.kv.groups[g].history = self.history
        if leases:
            from rdma_paxos_tpu.runtime import reads as reads_mod
            reads_mod.attach(self.shard)
        self.read_patience = int(read_patience)
        self.rng_reads = random.Random(f"shard-reads:{seed}")
        self.sess = self.kv.session(1)
        # per-group outstanding session write (the one-outstanding
        # protocol contract, per group): {key,val,req_id,op_id,to,
        # issued}
        self._out: List[Optional[dict]] = [None] * self.G
        self.write_patience = 14

    # ------------------------------------------------------------------

    def _frontiers(self) -> List[int]:
        """Per-group ABSOLUTE max commit frontier (rebase-corrected)."""
        res = self.shard.last
        return [int(res["commit"][g].max())
                + int(self.shard.rebased_total[g])
                for g in range(self.G)]

    def _issue(self, t: int, down) -> None:
        """Closed-loop SESSION write per group (one outstanding, the
        protocol contract; retransmit-on-failover, patience→ambiguous)
        plus the read-scaling mix — every operation lands in the
        checked history. Crashed-leader submissions land on an
        isolated claimant and stall — exactly the client experience
        of an outage."""
        for g in range(self.G):
            lead = self.shard.leader_hint(g)
            out = self._out[g]
            if out is not None:
                if t - out["issued"] > self.write_patience:
                    self.history.timeout(out["op_id"])   # fate unknown
                    self._out[g] = None
                elif lead >= 0 and lead != out["to"]:
                    # failover: retransmit the SAME req_id to the new
                    # leader (the dedup registry applies it once)
                    out["to"] = lead
                    self.sess.retransmit_put(out["key"], out["val"],
                                             out["req_id"],
                                             leader=lead)
                out = self._out[g]
            if out is None and lead >= 0:
                key = self.rng.choice(self.keys[g])
                self._vn += 1
                val = b"v%d" % self._vn
                _, rid = self.sess.put(key, val, leader=lead)
                op_id = self.history.op_id_for(
                    self.sess.conn_for(g), rid)
                self._out[g] = dict(key=key, val=val, req_id=rid,
                                    op_id=op_id, to=lead, issued=t)
        self._issue_reads(t, down)

    def _issue_reads(self, t: int, down) -> None:
        """Per-group lease reads at the group's serving holder and
        read-index reads queued at a random live replica — the fan-out
        ``place_leaders`` + per-group leases buy, checked
        linearizable."""
        hub = getattr(self.shard, "reads", None)
        if hub is None:
            return
        rr = self.rng_reads
        lm = self.shard.leases
        for g in range(self.G):
            if rr.random() < 0.5:
                target = lm.serving_holder(g) if lm is not None else -1
                if target < 0:
                    target = self.shard.leader_hint(g)
                if target >= 0 and target not in down:
                    self.kv.groups[g].get(target,
                                          rr.choice(self.keys[g]),
                                          linearizable=True)
            if rr.random() < 0.5:
                live = [r for r in range(self.R) if r not in down]
                if live:
                    f = rr.choice(live)
                    key = rr.choice(self.keys[g])
                    op_id = self.history.invoke("get", key, replica=f)

                    def done(status, value, _op=op_id):
                        if status == "ok":
                            self.history.ok(_op, value)
                        else:
                            self.history.fail(_op,
                                              reason="read_unserved")

                    hub.submit(
                        lambda g=g, f=f, k=key:
                        self.kv.groups[g].serve_local(f, k),
                        replica=f, group=g,
                        patience=self.read_patience, step0=t,
                        on_done=done)

    def _observe_clients(self, t: int) -> None:
        """Post-step completion sweep: a group's outstanding session
        write is acked once the leader's fold marks its req_id
        committed (the client-visible observation point)."""
        for g in range(self.G):
            out = self._out[g]
            if out is None:
                continue
            lead = self.shard.leader_hint(g)
            if lead < 0:
                continue
            self.kv.groups[g]._fold(lead)
            marks = self.kv.groups[g].last_req[lead]
            if marks.get(self.sess.conn_for(g), 0) >= out["req_id"]:
                self.history.ok(out["op_id"])
                self._out[g] = None

    def _check(self, res, t: int, violations: List[dict]) -> None:
        for g in range(self.G):
            try:
                self.checkers[g].check_step(
                    {k: res[k][g] for k in ("commit", "role", "term",
                                            "head", "apply", "end")},
                    step=t,
                    rebased_total=int(self.shard.rebased_total[g]))
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)

    def run(self) -> Dict:
        violations: List[dict] = []
        self.shard.place_leaders()
        crashed = -1
        down: set = set()
        timeouts: Dict[int, list] = {}
        f_at_crash: List[int] = []
        f_at_heal: List[int] = []
        for t in range(self.steps):
            self.history.set_clock(t)
            timeouts = {}
            if t == self.crash_step:
                crashed = self.shard.leader_hint(self.target)
                self.link.down.add(crashed)        # fail-stop, silent
                down = {crashed}
                f_at_crash = self._frontiers()
            if crashed >= 0 and t == self.crash_step + self.reelect_after:
                # a surviving member's election timer fires
                cand = next(r for r in range(self.R)
                            if r != crashed)
                timeouts[self.target] = [cand]
            self._issue(t, down)
            res = self.shard.step(timeouts=timeouts)
            self._observe_clients(t)
            self._check(res, t, violations)
        f_at_heal = self._frontiers()
        # settle: the crashed replica rejoins (state intact — a long
        # isolation, the fail-stop model crash_replica uses) and every
        # group converges
        self.link.down.discard(crashed)
        self.link.heal()
        down = set()
        for t in range(self.steps, self.steps + self.settle_steps):
            self.history.set_clock(t)
            self._issue(t, down)
            res = self.shard.step()
            self._observe_clients(t)
            self._check(res, t, violations)
        f_end = self._frontiers()
        # run end: fail still-queued reads, ambiguate unresolved writes
        self.history.set_clock(self.steps + self.settle_steps)
        if self.shard.reads is not None:
            self.shard.reads.fail_all("run end")
        for op_id in self.history.pending():
            self.history.timeout(op_id)
        for g in range(self.G):
            try:
                self.checkers[g].check_convergence(
                    self.shard.replayed[g])
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)
        others = [g for g in range(self.G) if g != self.target]
        others_advanced = all(f_at_heal[g] > f_at_crash[g]
                              for g in others)
        target_recovered = (f_end[self.target]
                            > f_at_crash[self.target])
        new_leader = self.shard.leader_hint(self.target)
        audit_summary = (self.shard.auditor.summary()
                         if self.shard.auditor is not None else None)
        audit_ok = (audit_summary is None
                    or audit_summary["findings"] == 0)
        linz = check_history(self.history.ops())
        ok = (not violations and others_advanced and target_recovered
              and new_leader >= 0 and new_leader != crashed
              and audit_ok and linz["ok"] is True)
        verdict = dict(
            ok=ok, seed=self.seed, steps=self.steps,
            target_group=self.target, crashed_leader=crashed,
            new_leader=new_leader,
            invariant_violations=violations,
            audit=audit_summary,
            linearizability=dict(ok=linz["ok"],
                                 violations=linz["violations"],
                                 undecided=linz["undecided"],
                                 ops=linz["ops"]),
            frontiers=dict(at_crash=f_at_crash, at_heal=f_at_heal,
                           at_end=f_end),
            others_advanced=others_advanced,
            target_recovered=target_recovered,
        )
        if self.shard.reads is not None:
            from rdma_paxos_tpu.runtime.reads import read_counts
            verdict["reads"] = dict(
                read_counts(self.shard.obs),
                hub=self.shard.reads.status(),
                leases=self.shard.leases.status())
        return verdict
