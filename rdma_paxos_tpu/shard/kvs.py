"""Sharded replicated KVS — router-directed puts/gets over G groups.

Each consensus group runs the standard single-group service
(:class:`~rdma_paxos_tpu.models.replicated_kvs.ReplicatedKVS` folding
its group's committed stream into per-replica device tables), reused
UNCHANGED through a ``SimCluster``-shaped per-group facade — sharding
adds routing on top, it does not fork the state-machine code. The
:class:`~rdma_paxos_tpu.shard.router.KeyRouter` decides which group
serves a key; sessions keep **per-group dedup sequence numbers** (one
``(client_id, req_id)`` stream per group, since groups commit
independently and a shared counter would leave holes every group's
dedup registry would misread); leader failover in one group re-routes
only that group's traffic — sessions against other groups never
notice.

Client-id namespacing: every stamped submission through this layer —
sessions AND direct ``ShardedKVS.put(client_id=...)`` calls — maps an
external client id ``c`` to conn ``c * G + g`` in group ``g``
(:meth:`ShardedKVS.conn_for`): injective over (client, group), so
dedup registries, span keys, and history records can never collide
across groups OR between the two submission paths within a group,
even though every group numbers its requests from 1.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.router import KeyLike, KeyRouter


class _GroupFacade:
    """A ``SimCluster``-shaped view of ONE group of a
    :class:`ShardedCluster` — exactly the surface ``ReplicatedKVS``
    consumes (``R``, ``submit``, ``replayed``, ``last``, ``obs``), so
    the single-group KVS folds a group's committed stream unchanged.
    This is the step/sim-boundary contract that keeps single-group the
    G=1 special case instead of a parallel code path."""

    def __init__(self, shard: ShardedCluster, group: int):
        self._shard = shard
        self.group = group
        self.R = shard.R

    @property
    def obs(self):
        return self._shard.obs

    @property
    def leases(self):
        """The sharded cluster's per-group LeaseManager (or None) —
        the single-group KVS consults it with this facade's group, so
        lease-path reads work identically through the facade."""
        return getattr(self._shard, "leases", None)

    @property
    def need_recovery(self):
        """This group's slice of the sharded ``{(g, r)}`` recovery
        set, in the single-group ``{r}`` shape the KVS serving gate
        consults."""
        return {r for (g, r) in self._shard.need_recovery
                if g == self.group}

    @property
    def read_blocked(self):
        """This group's slice of the repair pipeline's read-serving
        bar (same shape translation as ``need_recovery``)."""
        return {r for (g, r) in getattr(self._shard, "read_blocked",
                                        ())
                if g == self.group}

    def span_replica(self, r: int) -> int:
        """Namespaced span-track id for this group's replica ``r`` —
        the SAME ``g*R + r`` namespace the sharded cluster's
        append/commit/apply span stamps use, so session submit/ack
        events land on the right track."""
        return self._shard._span_rep(self.group, r)

    @property
    def replayed(self):
        return self._shard.replayed[self.group]

    @property
    def applied(self):
        """This group's ``[R]`` host apply cursors (the serving
        frontier gate in ``ReplicatedKVS.get`` compares them against
        the group's commit indices)."""
        return self._shard.applied[self.group]

    @property
    def last(self):
        last = self._shard.last
        if last is None:
            return None
        return {k: v[self.group] for k, v in last.items()}

    def submit(self, replica: int, payload: bytes,
               etype: EntryType = EntryType.SEND, conn: int = 1,
               req_id: int = 0) -> None:
        self._shard.submit(self.group, replica, payload, etype=etype,
                           conn=conn, req_id=req_id)


class ShardedKVS:
    """KVS service over a :class:`ShardedCluster`: every operation is
    routed to its key's group; reads/writes inside a group keep the
    single-group semantics (read-index linearizable GETs at the
    group's leader, weak GETs anywhere)."""

    def __init__(self, shard: ShardedCluster,
                 router: Optional[KeyRouter] = None, cap: int = 4096):
        self.shard = shard
        self.router = router if router is not None else shard.router
        if self.router.n_groups != shard.G:
            raise ValueError(
                f"router n_groups {self.router.n_groups} != cluster "
                f"groups {shard.G}")
        self.groups: List[ReplicatedKVS] = []
        for g in range(shard.G):
            kv = ReplicatedKVS(_GroupFacade(shard, g), cap=cap)
            kv.group = g
            self.groups.append(kv)

    # ---------------- routing ----------------

    def group_of(self, key: KeyLike) -> int:
        return self.router.group_of(key)

    def conn_for(self, client_id: int, group: int) -> int:
        """Group-namespaced conn id (``client_id * G + g``) — the ONE
        mapping every stamped submission through this layer uses
        (direct puts and sessions alike), so a direct put can never
        alias a session's dedup high-water mark within a group.
        ``client_id`` 0 (unstamped, dedup-exempt) stays 0."""
        if client_id <= 0:
            return client_id
        return client_id * self.shard.G + group

    def _leader(self, g: int, leader: Optional[int]) -> int:
        if leader is not None:
            return leader
        lead = self.shard.leader_hint(g)
        if lead < 0:
            raise RuntimeError(f"group {g} has no known leader")
        return lead

    def _gate(self, key: bytes) -> None:
        """Topology freeze gate: while an elastic cutover has ``key``'s
        range frozen, WRITES to it queue here (block) until the router
        swap lands or the window abandons — the only moment a key's
        group assignment may change out from under a submission. Reads
        never gate (the live router serves the old owner up to the
        atomic swap)."""
        topo = getattr(self.shard, "topology", None)
        if topo is not None:
            topo.gate_key(key)

    # ---------------- client API ----------------

    def put(self, key: bytes, val: bytes, *, client_id: int = 0,
            req_id: int = 0, leader: Optional[int] = None) -> int:
        """Route a PUT to its key's group (submitted at that group's
        leader, or ``leader`` when given). A stamped ``client_id`` is
        namespaced via :meth:`conn_for` — consistent with sessions.
        Returns the group id."""
        self._gate(key)
        g = self.group_of(key)
        self.groups[g].put(self._leader(g, leader), key, val,
                           client_id=self.conn_for(client_id, g),
                           req_id=req_id)
        return g

    def remove(self, key: bytes, *, client_id: int = 0,
               req_id: int = 0, leader: Optional[int] = None) -> int:
        self._gate(key)
        g = self.group_of(key)
        self.groups[g].remove(self._leader(g, leader), key,
                              client_id=self.conn_for(client_id, g),
                              req_id=req_id)
        return g

    def get(self, key: bytes, *, linearizable: bool = False,
            replica: Optional[int] = None) -> Optional[bytes]:
        """Read ``key`` from its group. Linearizable reads default to
        the group's lease-serving replica (the holder — how
        ``place_leaders`` spreads read serving across the R replicas)
        falling back to the leader for the read-index path; weak
        reads go to ``replica`` (or the leader by default)."""
        g = self.group_of(key)
        if replica is None:
            lm = getattr(self.shard, "leases", None)
            if linearizable and lm is not None:
                replica = lm.serving_holder(g)
            else:
                replica = -1
            if replica < 0:
                replica = self.shard.leader_hint(g)
            if replica < 0:
                replica = 0
        return self.groups[g].get(replica, key,
                                  linearizable=linearizable)

    def session(self, client_id: int) -> "ShardedSession":
        return ShardedSession(self, client_id)

    def transact(self, writes, reads=()):
        """Admit one cross-group atomic transaction (txn/api.py):
        ``writes`` are ``(op_name, key, value)`` triples, op_name in
        {put, rm, incr, sadd, max}. Requires ``txn.attach_coordinator``
        on a ``txn=True`` cluster. Returns a ``TxnHandle``."""
        from rdma_paxos_tpu.txn.api import transact
        return transact(self, writes, reads)


class ShardedSession:
    """A retransmitting client over the sharded keyspace.

    One underlying single-group ``ClientSession`` per group, created
    lazily, each with its own req_id stream (per-group dedup sequence
    numbers) and a group-namespaced conn id (``client_id * G + g``).
    The single-group protocol contract holds PER GROUP: at most one
    request outstanding per group's session; requests to different
    groups may be in flight concurrently (they commit independently).

    Failover: :meth:`retransmit_put` re-sends a known ``(key,
    req_id)`` verbatim to the key's group's CURRENT leader — after a
    leader crash in one group, only that group's traffic re-routes.
    """

    def __init__(self, kvs: ShardedKVS, client_id: int):
        if client_id <= 0:
            raise ValueError("client_id must be positive")
        self.kvs = kvs
        self.client_id = client_id
        self._sess: Dict[int, object] = {}

    def conn_for(self, group: int) -> int:
        """The group-namespaced conn id riding M_CONN for this
        session's entries in ``group``'s log (the shared
        ``ShardedKVS.conn_for`` mapping, so direct stamped puts with
        the same external client_id hit the SAME dedup stream)."""
        return self.kvs.conn_for(self.client_id, group)

    def _group_session(self, g: int):
        sess = self._sess.get(g)
        if sess is None:
            sess = self.kvs.groups[g].session(self.conn_for(g))
            self._sess[g] = sess
        return sess

    def put(self, key: bytes, val: bytes, *,
            leader: Optional[int] = None) -> tuple:
        """Submit a PUT; returns ``(group, req_id)`` — keep the pair to
        retransmit after a timeout or that group's leader failover."""
        self.kvs._gate(key)
        g = self.kvs.group_of(key)
        rid = self._group_session(g).put(
            self.kvs._leader(g, leader), key, val)
        return g, rid

    def remove(self, key: bytes, *,
               leader: Optional[int] = None) -> tuple:
        self.kvs._gate(key)
        g = self.kvs.group_of(key)
        rid = self._group_session(g).remove(
            self.kvs._leader(g, leader), key)
        return g, rid

    def retransmit_put(self, key: bytes, val: bytes, req_id: int, *,
                       leader: Optional[int] = None) -> int:
        """Resend an earlier PUT verbatim to the key's group's current
        leader. Safe any number of times — the group's dedup registry
        applies it exactly once, surviving failover and restarts."""
        self.kvs._gate(key)
        g = self.kvs.group_of(key)
        self._group_session(g).retransmit_put(
            self.kvs._leader(g, leader), key, val, req_id)
        return g

    def req_id(self, group: int) -> int:
        """The session's current (last issued) req_id in ``group``."""
        sess = self._sess.get(group)
        return sess.req_id if sess is not None else 0
